"""Cross-peer pipeline serving: coordinator + worker task handlers.

BASELINE config 4 (zephyr-7b split across two peers). The reference's
coordinator never survived in its repo — only the worker loop (reference
node.py:48-294) and the protocol constants; this module implements BOTH
halves the TPU-native way:

- Workers hold a StageRunner (layers [a, b) on their own mesh) and answer
  `task` messages of kind part_load / part_forward / part_release
  (protocol.TASK_PART_LOAD/TASK_PART_FORWARD). Hidden states travel as
  binary tensor frames (protocol.encode_binary), not JSON float lists.
- `PipelineCoordinator` drives a generation: prompt ids → stage 0 →
  hidden → stage 1 → ... → logits → sample host-side → feed the token
  back through the chain at the next offset. Per-stage KV caches live on
  the workers, so each decode step moves only [B, 1, D] activations.

The coordinator is itself a mesh peer: it speaks to stage workers over
the same WebSocket connections the gossip/generation traffic uses.

Failover (docs/ROBUSTNESS.md): stage failures are typed (StageDead /
StageTimeout / StageError); on StageDead the coordinator re-places the
dead stage's layer range onto a replacement peer under a bumped stage
epoch (late traffic to/from the old occupant is refused), and in-flight
generations resume by re-prefilling prompt + accepted-so-far — the
coordinator's accepted-token stream is the recovery truth.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from urllib.parse import urlparse

import numpy as np

from .. import protocol
from ..clock import get_clock
from ..health import get_recorder
from ..metrics import get_registry
from ..tracing import extract_trace, get_tracer, inject_trace, use_trace_ctx
from ..utils import log_task_exception, new_id

logger = logging.getLogger("bee2bee_tpu.pipeline")

# failover observability (metrics.py): the ROBUSTNESS layer's health is
# invisible without these — a mesh that fails over constantly "works"
# while burning re-prefills. kind labels are bounded by the task-kind set.
_C_STAGE_TASKS = get_registry().counter(
    "pipeline.stage_tasks", "stage tasks sent by the coordinator, by kind"
)
_C_RECOVERIES = get_registry().counter(
    "pipeline.recoveries", "coordinator recover() rebuilds"
)
_C_STAGES_REPLACED = get_registry().counter(
    "pipeline.stages_replaced", "dead stages re-placed onto new peers"
)
_C_EPOCH_BUMPS = get_registry().counter(
    "pipeline.epoch_bumps", "stage-epoch bumps (one per chain rebuild)"
)
_C_SESSION_FAILOVERS = get_registry().counter(
    "pipeline.session_failovers", "batched-session failover attempts"
)
# migration-preferring failover (ISSUE 9): when the chain is still ALIVE
# (StageTimeout/StageError, epoch unchanged), the stage KV caches hold
# every written position — resume decode in place instead of releasing
# and re-prefilling prompt+accepted. State "migrates" zero bytes: it
# stays where it is. Re-prefill remains the rung for unrecoverable state
# (StageDead: the dead stage's cache is gone with its process).
_C_RESUMES_IN_PLACE = get_registry().counter(
    "pipeline.resumes_in_place",
    "failovers resumed on live stage caches without re-prefill",
)
# worker-side stage task timing (ISSUE 10) rides the telemetry digest
# (health.DIGEST_HISTOGRAMS) so a coordinator can weigh stage compute
# against hop latency when resolving the microbatch depth. The histogram
# itself is observed in engine/stage_runner.py, INSIDE the concurrency
# gate: queue/semaphore wait must not inflate the p50 the heuristic
# divides by, or a saturated worker reads as "slow compute" and the auto
# depth under-resolves exactly when more overlap would pay.

DEFAULT_STEP_TIMEOUT = 120.0
# generation-level failover policy defaults (PipelineCoordinator knobs)
DEFAULT_FAILOVER_RETRIES = 2
DEFAULT_FAILOVER_BACKOFF_S = 0.5
DEFAULT_GENERATION_DEADLINE_S = 600.0


# ----------------------------------------------------------- error taxonomy


class StageError(RuntimeError):
    """A stage worker answered TASK_ERROR: the stage is alive and
    reachable but the task failed. Retryable (bounded); never triggers
    re-placement on its own."""

    def __init__(self, message: str, peer: str | None = None,
                 stage: int | None = None):
        super().__init__(message)
        self.peer = peer
        self.stage = stage


class StageDead(StageError):
    """The stage's transport is gone (connection lost, peer unknown, or a
    mid-chain successor vanished): a reply can never arrive. Failover
    re-places the stage on a replacement peer and resumes by re-prefill."""


class StageTimeout(StageError):
    """No reply within the step timeout. The stage may be alive but
    wedged or black-holed; blame can't be localized through a relay
    chain, so timeouts retry the existing chain instead of re-placing."""


# --------------------------------------------------------------- node mixin


class StageTaskMixin:
    """Task-protocol handlers mixed into P2PNode (kept separate so the
    mesh core stays readable; node.py wires _handle_task/_handle_result
    into its dispatch table)."""

    def add_stage_runner(self, runner) -> None:
        """Host a pipeline stage (StageRunner) on this node. The mesh
        addresses runners by the COORDINATOR'S model string, which under
        `--model auto` differs from the resolved config name — register
        both so part_forward/decode_run find the runner either way."""
        self.stage_runners[runner.model_cfg.name] = runner
        requested = getattr(runner, "requested_model", None)
        if requested and requested != runner.model_cfg.name:
            self.stage_runners[requested] = runner

    async def _peer_ws(self, peer_id: str | None, what: str):
        """Resolve a peer's live ws or raise StageDead — the relay/ring
        handlers' shared lookup (one place to change if peer bookkeeping
        does). Typed so a mid-chain death classifies as `dead` at the
        origin, not as a generic task error."""
        if not peer_id:
            raise StageDead(f"{what}: peer unknown (dropped mid-task?)")
        async with self._lock:
            info = self.peers.get(peer_id)
        if info is None:
            raise StageDead(f"{what}: peer {peer_id!r} gone", peer=peer_id)
        return info["ws"]

    async def _handle_task(self, ws, data):
        # adopt the coordinator's trace context before dispatch: the
        # stage.task span (and any onward relay/ring frame this worker
        # sends, which inject_trace stamps from the contextvar) parents
        # under the request that caused it — every stage's /trace
        # fragment then stitches into the coordinator's timeline
        with use_trace_ctx(extract_trace(data)):
            with get_tracer().span(
                "stage.task", kind=data.get("kind"), model=data.get("model")
            ) as sp:
                # the stage index rides the span so the bubble-fraction
                # derivation (health.bubble_from_spans) can attribute
                # busy time per stage, not just per node
                runner = self.stage_runners.get(data.get("model"))
                if runner is not None:
                    sp.attrs["stage"] = runner.spec.stage
                await self._dispatch_task(ws, data)

    async def _dispatch_task(self, ws, data):
        kind = data.get("kind")
        task_id = data.get("task_id")

        async def fail(error: str, error_kind: str = protocol.ERR_KIND_ERROR):
            # relayed tasks report failure to the ORIGIN coordinator, not
            # the previous stage (which isn't waiting on anything)
            origin = data.get("origin_peer")
            if origin:
                try:
                    origin_ws = await self._peer_ws(origin, "task error routing")
                except RuntimeError:
                    origin_ws = None
                if origin_ws is not None:
                    await self._send(
                        origin_ws,
                        protocol.msg(
                            protocol.TASK_ERROR,
                            task_id=data.get("origin_task_id"), error=error,
                            error_kind=error_kind,
                        ),
                    )
                    return
            await self._send(
                ws, protocol.msg(protocol.TASK_ERROR, task_id=task_id,
                                 error=error, error_kind=error_kind)
            )

        try:
            if kind == protocol.TASK_PART_LOAD:
                await self._task_part_load(ws, data)
            elif kind == protocol.TASK_PART_FORWARD:
                await self._task_part_forward(ws, data)
            elif kind == protocol.TASK_PART_FORWARD_RELAY:
                await self._task_part_forward_relay(ws, data)
            elif kind == protocol.TASK_DECODE_RUN:
                await self._task_decode_run(ws, data)
            elif kind == protocol.TASK_LAYER_FORWARD_TRAIN:
                await self._task_forward_train(ws, data)
            elif kind == protocol.TASK_LAYER_BACKWARD:
                await self._task_backward(ws, data)
            elif kind == "part_release":
                runner = self.stage_runners.get(data.get("model"))
                if runner is not None:
                    runner.release(data.get("request_id"))
                await self._send(
                    ws, protocol.msg(protocol.RESULT, task_id=task_id, ok=True)
                )
            else:
                await fail(f"unknown task kind {kind!r}")
        except Exception as e:  # noqa: BLE001 — worker must answer, not die
            logger.exception("task %s failed", kind)
            await fail(
                f"{type(e).__name__}: {e}",
                protocol.ERR_KIND_DEAD if isinstance(e, StageDead)
                else protocol.ERR_KIND_ERROR,
            )

    async def _task_part_load(self, ws, data):
        from ..engine.stage_runner import StageRunner

        task_id = data.get("task_id")
        epoch = int(data.get("epoch", 0))
        existing = self.stage_runners.get(data.get("model"))
        if existing is not None and existing.matches_load(data):
            # failover idempotency: re-loading the SAME stage is a no-op
            # (no recompile) that adopts the request's epoch and re-dials
            # the relay successor below — recover() re-wires surviving
            # stages this way. max() so a straggling retry from an older
            # attempt can never downgrade the epoch.
            runner = existing
            runner.epoch = max(runner.epoch, epoch)
        else:
            loop = asyncio.get_running_loop()
            runner = await loop.run_in_executor(
                None,
                lambda: StageRunner(
                    data["model"],
                    n_stages=int(data["n_stages"]),
                    stage=int(data["stage"]),
                    checkpoint_path=data.get("checkpoint_path"),
                    max_seq_len=int(data.get("max_seq_len", 2048)),
                    dtype=data.get("dtype", "bfloat16"),
                    rng_seed=int(data.get("rng_seed", 0)),
                    quantize=data.get("quantize", "none"),
                    epoch=epoch,
                ),
            )
            self.add_stage_runner(runner)
        # relay chaining: dial the NEXT stage so hidden states can hop
        # worker→worker without bouncing through the coordinator
        relay = False
        next_addr = data.get("next_addr")
        if next_addr:
            try:
                # plain peer dial — NOT connect_bootstrap: bootstrap addrs
                # are redialed forever even after a clean GOODBYE, which
                # would chase a retired successor for the process lifetime
                if self.peer_for_addr(next_addr) or await self._connect_peer(next_addr):
                    for _ in range(50):
                        pid = self.peer_for_addr(next_addr)
                        if pid:
                            self.stage_next[data["model"]] = pid
                            relay = True
                            break
                        await self.clock.sleep(0.1)
            except Exception:  # noqa: BLE001 — relay optional; fall back
                logger.exception("next-stage dial %s failed", next_addr)
        await self._send(
            ws,
            protocol.msg(
                protocol.RESULT, task_id=task_id, ok=True,
                # relay: can this stage chain forward (last stage answers
                # the origin instead, so it chains by definition).
                # ring: did the successor dial actually succeed — the last
                # stage's wrap-around link to stage 0 enables burst decode.
                info={**runner.info,
                      "relay": relay or runner.spec.is_last,
                      "ring": relay,
                      # this stage's decode_run knows temperature/seed
                      # fields (round 5) — a coordinator must NOT route
                      # sampled requests around a ring of older stages
                      # that would silently argmax them
                      "ring_sampling": relay},
            ),
        )

    async def _run_stage_forward(self, data) -> np.ndarray:
        """Shared parse + executor dispatch for both forward task kinds:
        pull x off the binary frame, coerce offset/write_mask/gather
        (int | [B] lists — the batched session), run the stage."""
        runner = self.stage_runners.get(data.get("model"))
        if runner is None:
            raise RuntimeError(f"no stage loaded for model {data.get('model')!r}")
        epoch = data.get("epoch")
        if epoch is not None and int(epoch) != getattr(runner, "epoch", 0):
            # late traffic addressed to a replaced occupant (or a stage
            # that missed a re-load): refuse instead of corrupting caches
            raise RuntimeError(
                f"stale stage epoch {epoch} (stage now at {runner.epoch})"
            )
        x = data["_tensors"]["x"]
        offset = data.get("offset", 0)
        if not isinstance(offset, int):
            offset = np.asarray(offset, np.int32)
        mask = data.get("write_mask")
        if mask is not None:
            mask = np.asarray(mask, bool)
        gather = data.get("gather")
        if gather is not None:
            gather = np.asarray(gather, np.int32)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None,
            lambda: runner.forward(
                data["request_id"], x, offset, write_mask=mask, gather=gather
            ),
        )

    async def _task_part_forward(self, ws, data):
        out = await self._run_stage_forward(data)
        frame = protocol.encode_binary(
            # inject: the RESULT carries this worker's span context back
            # (a coordinator-side consumer can link reply to stage span)
            inject_trace(
                protocol.msg(protocol.RESULT, task_id=data.get("task_id"), ok=True)
            ),
            {"out": out},
        )
        await self._send(ws, frame)

    async def _task_part_forward_relay(self, ws, data):
        """Relay-chained forward: run this stage, then hand the output
        DIRECTLY to the next stage (or, on the last stage, answer the
        origin coordinator). Per decode step the coordinator pays one
        send + one receive instead of two round trips per stage, and
        hidden states never transit the coordinator at all."""
        # first hop (coordinator → stage 0) carries no origin fields: the
        # sender IS the origin and its task_id is the reply correlation id
        if not data.get("origin_peer"):
            sender = await self._peer_for(ws)
            if sender is None:  # a None origin would misroute the RESULT
                raise RuntimeError("relay sender unknown (dropped mid-task?)")
            data["origin_peer"] = sender
            data["origin_task_id"] = data.get("task_id")
        out = await self._run_stage_forward(data)
        runner = self.stage_runners[data["model"]]
        if runner.spec.is_last:
            origin_ws = await self._peer_ws(data.get("origin_peer"), "relay origin")
            frame = protocol.encode_binary(
                inject_trace(protocol.msg(
                    protocol.RESULT, task_id=data.get("origin_task_id"), ok=True
                )),
                {"out": out},
            )
            await self._send(origin_ws, frame)
            return
        next_ws = await self._peer_ws(
            self.stage_next.get(data["model"]), "relay next stage"
        )
        fields = {
            k: data[k]
            for k in ("model", "request_id", "offset", "write_mask", "gather",
                      "origin_peer", "origin_task_id", "epoch")
            if k in data
        }
        frame = protocol.encode_binary(
            # inject under THIS stage's span (set by _handle_task), so the
            # next stage's span parents stage-under-stage along the chain
            inject_trace(protocol.msg(
                protocol.TASK, kind=protocol.TASK_PART_FORWARD_RELAY,
                task_id=new_id("task"), **fields,
            )),
            {"x": out},
        )
        await self._send(next_ws, frame)

    async def _task_forward_train(self, ws, data):
        """Training forward: run the stage uncached, retaining activations
        for the backward (the reference's layer_forward_train worker task,
        reference node.py:99-130, realized as real stage VJP state)."""
        runner = self.stage_runners.get(data.get("model"))
        if runner is None:
            raise RuntimeError(f"no stage loaded for model {data.get('model')!r}")
        loop = asyncio.get_running_loop()
        out = await loop.run_in_executor(
            None,
            lambda: runner.forward_train(data["request_id"], data["_tensors"]["x"]),
        )
        await self._send(ws, protocol.encode_binary(
            protocol.msg(protocol.RESULT, task_id=data.get("task_id"), ok=True),
            {"out": out},
        ))

    async def _task_backward(self, ws, data):
        """Training backward: VJP against the retained activation, SGD on
        this stage's params, dX back to the coordinator (reference
        node.py:131-182's layer_backward, with real gradients)."""
        runner = self.stage_runners.get(data.get("model"))
        if runner is None:
            raise RuntimeError(f"no stage loaded for model {data.get('model')!r}")
        loop = asyncio.get_running_loop()
        dx = await loop.run_in_executor(
            None,
            lambda: runner.backward(
                data["request_id"], data["_tensors"]["dy"],
                float(data.get("lr", 1e-3)),
            ),
        )
        msg = protocol.msg(protocol.RESULT, task_id=data.get("task_id"), ok=True)
        if dx is None:  # first stage: ids take no gradient
            await self._send(ws, msg)
        else:
            await self._send(ws, protocol.encode_binary(msg, {"dx": dx}))

    _RING_FIELDS = ("model", "request_id", "offset", "k", "eos", "gather",
                    "origin_peer", "origin_task_id", "temperature", "seed",
                    "epoch")
    BURST_STALE_S = 600.0

    @staticmethod
    def _ring_sample(logits: np.ndarray, data: dict) -> int:
        """Last-stage sampling for ring bursts. Greedy is plain argmax;
        temperature>0 draws from the softmax with an rng keyed on
        (coordinator seed, token position) — the position makes each
        draw's stream unique while keeping the whole rollout reproducible
        from the seed, independent of burst size (same semantics as
        PipelineCoordinator._sample, just computed where the logits are)."""
        temp = float(data.get("temperature") or 0.0)
        if temp <= 0.0:
            return int(np.argmax(logits))
        pos = int(np.asarray(data["offset"]).reshape(-1)[0])
        rng = np.random.default_rng((int(data.get("seed") or 0), pos))
        z = logits.astype(np.float64) / max(temp, 1e-6)
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(len(p), p=p))

    async def _task_decode_run(self, ws, data):
        """Ring-burst decode (kind=decode_run): the coordinator sends ONE
        message for up to k tokens. Each token circulates stage0→…→last;
        the LAST stage samples (argmax, or a seeded softmax draw when the
        request carries temperature>0) and feeds the new token straight
        back to stage 0 over the ring link, accumulating the burst
        locally; the coordinator hears back once per burst, not once per
        token."""
        runner = self.stage_runners.get(data.get("model"))
        if runner is None:
            raise RuntimeError(f"no stage loaded for model {data.get('model')!r}")
        if not data.get("origin_peer"):
            sender = await self._peer_for(ws)
            if sender is None:  # a None origin would misroute the RESULT
                raise RuntimeError("ring sender unknown (dropped mid-task?)")
            data["origin_peer"] = sender
            data["origin_task_id"] = data.get("task_id")
        if runner.spec.is_first and "x" not in (data.get("_tensors") or {}):
            data["_tensors"] = {
                "x": np.asarray([[int(data["token"])]], np.int32)
            }
        data.setdefault("gather", [0])  # last stage returns [1, V]
        out = await self._run_stage_forward(data)
        nxt = self.stage_next.get(data["model"])
        if not runner.spec.is_last:
            next_ws = await self._peer_ws(nxt, "ring next stage")
            fields = {k: data[k] for k in self._RING_FIELDS if k in data}
            await self._send(next_ws, protocol.encode_binary(
                inject_trace(protocol.msg(
                    protocol.TASK, kind=protocol.TASK_DECODE_RUN,
                    task_id=new_id("task"), **fields)),
                {"x": out},
            ))
            return
        # ---- last stage: sample, accumulate, circulate or answer ----
        tok = self._ring_sample(out[0], data)
        otid = data["origin_task_id"]
        now = self.clock.time()
        for stale in [k for k, v in self.stage_bursts.items()
                      if now - v["t"] > self.BURST_STALE_S]:
            self.stage_bursts.pop(stale, None)
        burst = self.stage_bursts.setdefault(otid, {"tokens": [], "t": now})
        burst["t"] = now  # refresh: a live burst must never be reaped
        eos = data.get("eos")
        k = int(data.get("k", 1))
        stopped = eos is not None and tok == eos
        if not stopped:
            burst["tokens"].append(tok)
        if stopped or len(burst["tokens"]) >= k:
            tokens = burst["tokens"]
            self.stage_bursts.pop(otid, None)
            origin_ws = await self._peer_ws(data["origin_peer"], "ring origin")
            await self._send(origin_ws, inject_trace(protocol.msg(
                protocol.RESULT, task_id=otid, ok=True,
                tokens=tokens, stopped=stopped,
            )))
            return
        try:
            next_ws = await self._peer_ws(nxt, "ring link to stage 0")
        except RuntimeError:
            self.stage_bursts.pop(otid, None)
            raise
        fields = {key: data[key] for key in self._RING_FIELDS if key in data}
        fields["offset"] = int(np.asarray(data["offset"]).reshape(-1)[0]) + 1
        fields["token"] = tok
        await self._send(next_ws, inject_trace(protocol.msg(
            protocol.TASK, kind=protocol.TASK_DECODE_RUN,
            task_id=new_id("task"), **fields,
        )))

    async def _handle_result(self, ws, data):
        """RESULT / TASK_ERROR → resolve the matching pending future."""
        task_id = data.get("task_id")
        async with self._pending_lock:
            fut = self._pending.get(task_id)
        if fut and not fut.done():
            fut.set_result(data)

    async def run_stage_task(
        self,
        peer_id: str,
        kind: str,
        fields: dict,
        tensors: dict | None = None,
        timeout: float = DEFAULT_STEP_TIMEOUT,
        reply_from: str | None = None,  # peer whose ws carries the REPLY
        # (relay/ring: the LAST stage answers, not the stage we send to)
    ) -> dict:
        """Send one task to a peer and await its RESULT (tensors included
        under '_tensors'). Failures raise the typed taxonomy: StageDead
        (transport gone / peer unknown / worker reported a dead
        successor), StageTimeout (no reply in `timeout`), StageError (the
        worker answered TASK_ERROR)."""
        async with self._lock:
            info = self.peers.get(peer_id)
            reply_info = self.peers.get(reply_from) if reply_from else info
        if info is None:
            raise StageDead(f"unknown peer {peer_id!r}", peer=peer_id)
        if reply_info is None:
            raise StageDead(f"unknown reply peer {reply_from!r}", peer=reply_from)
        task_id = new_id("task")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        async with self._pending_lock:
            self._pending[task_id] = fut
            # the connection the reply rides on: its death means the reply
            # can never arrive — fail fast instead of waiting out the
            # timeout. (Mid-chain stage deaths are covered separately: the
            # predecessor's failed send routes a TASK_ERROR to the origin.)
            self._pending_ws[task_id] = reply_info["ws"]
        _C_STAGE_TASKS.inc(kind=kind)
        # trace_ctx rides every stage task: the worker's stage.task span
        # (and relayed hops beyond it) parents under the caller's span
        message = inject_trace(
            protocol.msg(protocol.TASK, kind=kind, task_id=task_id, **fields)
        )
        try:
            try:
                if tensors:
                    await self._send(
                        info["ws"], protocol.encode_binary(message, tensors)
                    )
                else:
                    await self._send(info["ws"], message)
            except StageError:
                raise
            except Exception as e:  # ConnectionClosed/OSError under the send
                raise StageDead(
                    f"send {kind} to {peer_id!r} failed: {e}", peer=peer_id
                ) from e
            try:
                result = await self.clock.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                raise StageTimeout(
                    f"{kind} on {peer_id!r}: no reply in {timeout:.0f}s",
                    peer=reply_from or peer_id,
                ) from None
        finally:
            async with self._pending_lock:
                self._pending.pop(task_id, None)
                self._pending_ws.pop(task_id, None)
        if result.get("type") == protocol.TASK_ERROR or result.get("error"):
            err = result.get("error") or "task failed"
            if result.get("error_kind") == protocol.ERR_KIND_DEAD:
                raise StageDead(err, peer=peer_id)
            raise StageError(err, peer=peer_id)
        return result


# ------------------------------------------------------------- coordinator


def resolve_microbatches(
    stage_addrs: list,
    stage_task_ms: list | None = None,
    hop_rtt_ms: list | None = None,
    max_depth: int = 4,
) -> int:
    """The `--microbatches auto` heuristic: microbatch overlap pays only
    when stages compute in PARALLEL, i.e. they run on different hosts —
    then group g+1's stage-0 compute genuinely overlaps group g's stage-1
    compute. Stages sharing one host contend for the same cores, so the
    M× extra wire messages buy nothing (measured on the loopback split:
    docs/PERF.md "Microbatch overlap"). Unknown topology resolves to 1 —
    never gamble hop cost on a guess.

    With telemetry (ISSUE 10) the distinct-host answer graduates from the
    binary guess to a DEPTH: `stage_task_ms` (per-stage p50 task time from
    the gossiped digests' `pipeline.stage_task_ms` histogram) and
    `hop_rtt_ms` (coordinator→stage ping RTTs) give the classic pipeline
    fill bound — to keep S stages busy, the in-flight window must cover
    one token's full wall time, S·(compute + hop), at `compute` per stage:

        depth ≈ round(S · (1 + hop / compute))

    clamped to [2, max_depth] (the session clamps to max_batch again).
    Pure compute-bound stages (hop ≪ compute) resolve to the stage count;
    hop-dominated topologies ask for more in-flight chains to hide the
    wire. Absent/empty telemetry falls back to the legacy answer of 2."""
    hosts = set()
    for a in stage_addrs:
        if not a:
            return 1
        try:
            host = urlparse(a).hostname or str(a)
        except ValueError:
            return 1
        if host == "::1" or host.startswith("127."):
            # loopback aliases are all the same machine — mixed
            # localhost/127.0.0.1 worker flags must not read as two hosts
            host = "localhost"
        hosts.add(host)
    if len(hosts) < 2:
        return 1
    timings = [float(t) for t in (stage_task_ms or []) if t]
    rtts = [float(r) for r in (hop_rtt_ms or []) if r]
    if not timings or not rtts:
        return 2  # distinct hosts, no telemetry: the legacy binary guess
    compute = sorted(timings)[len(timings) // 2]  # median stage compute
    hop = sorted(rtts)[len(rtts) // 2] / 2.0  # one-way hop estimate
    depth = round(len(stage_addrs) * (1.0 + hop / max(compute, 1e-3)))
    return max(2, min(depth, max_depth))


class PipelineCoordinator:
    """Drive generation across stage workers (reference contrast:
    node.py:249-277 chains hf_part_forward hops; here the chain carries a
    KV-cached decode loop with host-side sampling at the coordinator)."""

    def __init__(
        self,
        node,
        model: str,
        stage_peers: list[str],  # peer_ids in stage order (stage i = peers[i])
        max_seq_len: int = 2048,
        dtype: str = "bfloat16",
        rng_seed: int = 0,
        quantize: str = "none",  # int8: each stage quantizes ITS slice
        step_timeout: float = DEFAULT_STEP_TIMEOUT,
        max_failover_retries: int = DEFAULT_FAILOVER_RETRIES,
        failover_backoff_s: float = DEFAULT_FAILOVER_BACKOFF_S,
        generation_deadline_s: float = DEFAULT_GENERATION_DEADLINE_S,
    ):
        self.node = node
        self.clock = getattr(node, "clock", None) or get_clock()
        self.model = model
        self.stage_peers = list(stage_peers)
        self.max_seq_len = max_seq_len
        self.dtype = dtype
        self.rng_seed = rng_seed
        self.quantize = quantize
        # failover policy (docs/ROBUSTNESS.md): bounded retries with
        # exponential backoff under a per-generation wall-clock deadline
        self.step_timeout = step_timeout
        self.max_failover_retries = max_failover_retries
        self.failover_backoff_s = failover_backoff_s
        self.generation_deadline_s = generation_deadline_s
        # stage epoch: bumped by recover(); stamped into every task so
        # late replies/relays from a replaced occupant are refused
        self.epoch = 0
        # single-flight: concurrent generations that all caught the same
        # stage failure must share ONE rebuild, not ping-pong epoch bumps
        # that invalidate each other's chains
        self._recover_lock = asyncio.Lock()
        self.checkpoint_path: str | None = None
        self.load_timeout = 600.0
        # set by load(): every stage dialed its successor, so chains can
        # relay worker→worker instead of round-tripping the coordinator
        self.relay_ok = False
        # the ring closes (last stage → stage 0): greedy decode can run
        # K-token bursts with last-stage sampling
        self.ring_ok = False
        # every stage also speaks the burst temperature/seed fields
        self.ring_sampling_ok = False
        self.ring_burst = 16  # tokens per coordinator round trip

    async def load(
        self, checkpoint_path: str | None = None, timeout: float = 600.0
    ) -> list[dict]:
        """part_load every stage concurrently; returns their stage infos.
        `timeout` covers checkpoint read + compile per stage (a 7B half
        takes minutes — far beyond the per-step default). The checkpoint
        path and timeout are remembered so recover() can rebuild a dead
        stage from the same source."""
        self.checkpoint_path = checkpoint_path
        self.load_timeout = timeout
        return await self._load_stages(timeout)

    async def _load_stages(self, timeout: float) -> list[dict]:
        """part_load all stages at the current epoch (idempotent for
        already-loaded stages — they adopt the epoch and re-dial their
        relay successor). If a long-lived worker reports a HIGHER epoch
        (it outlived a coordinator restart), adopt the max and re-load
        once so every stage agrees."""
        for _ in range(2):
            # each stage gets its successor's dial address for relay chaining
            async with self.node._lock:
                addrs = [
                    (self.node.peers.get(pid) or {}).get("addr")
                    for pid in self.stage_peers
                ]
            results = await asyncio.gather(
                *(
                    self.node.run_stage_task(
                        peer,
                        protocol.TASK_PART_LOAD,
                        {
                            "model": self.model,
                            "n_stages": len(self.stage_peers),
                            "stage": s,
                            "max_seq_len": self.max_seq_len,
                            "dtype": self.dtype,
                            "rng_seed": self.rng_seed,
                            "quantize": self.quantize,
                            "checkpoint_path": self.checkpoint_path,
                            "epoch": self.epoch,
                            # wrap-around: the LAST stage dials stage 0,
                            # closing the ring for burst decode
                            "next_addr": (
                                addrs[(s + 1) % len(self.stage_peers)]
                                if len(self.stage_peers) > 1 else None
                            ),
                        },
                        timeout=timeout,
                    )
                    for s, peer in enumerate(self.stage_peers)
                )
            )
            infos = [r.get("info", {}) for r in results]
            top = max(
                [self.epoch, *(int(i.get("epoch") or 0) for i in infos)]
            )
            if top == self.epoch:
                break
            self.epoch = top
        self.relay_ok = len(infos) > 0 and all(i.get("relay") for i in infos)
        self.ring_ok = (
            len(infos) > 1 and all(i.get("ring") for i in infos)
        )
        # sampled bursts need every stage to SPEAK the temperature/seed
        # fields; an older stage would ignore them and argmax silently
        self.ring_sampling_ok = (
            self.ring_ok and all(i.get("ring_sampling") for i in infos)
        )
        return infos

    # ------------------------------------------------------------- failover

    def stage_health(self) -> list[dict]:
        """Per-stage health off the node's existing ping bookkeeping:
        'online', 'unreachable' (3 missed pings), or 'dead' (no
        connection at all). Sync read on the loop thread — same
        justification as P2PNode.peer_for_addr."""
        out = []
        for s, pid in enumerate(self.stage_peers):
            info = self.node.peers.get(pid)
            status = "dead" if info is None else info.get("health", "online")
            out.append({"stage": s, "peer": pid, "status": status})
        return out

    def _pick_replacement(self, exclude: set[str]) -> str | None:
        """Best replacement peer for a dead stage: online peers outside
        the pipeline, capacity-advertising ones (hello's accepts_stages)
        first, then lowest RTT."""
        cands = []
        for pid, info in list(self.node.peers.items()):
            if pid in exclude or info.get("health") != "online":
                continue
            cands.append((
                0 if info.get("accepts_stages") else 1,
                info.get("rtt_ms") or float("inf"),
                pid,
            ))
        return sorted(cands)[0][2] if cands else None

    async def recover(
        self, timeout: float | None = None, observed_epoch: int | None = None,
    ) -> list[tuple[int, str]]:
        """Re-place every dead/unreachable stage on a replacement peer and
        rebuild the whole chain under a bumped stage epoch: survivors
        adopt the epoch and re-dial their relay successors (idempotent
        part_load — no recompile); replacements load the dead stage's
        layer range from the same checkpoint path (or the deterministic
        seed init). Returns [(stage, new_peer_id)] for what moved. Raises
        StageDead when a dead stage has no replacement candidate.

        Single-flight: pass `observed_epoch` (the epoch at the moment the
        failure was caught) and concurrent callers share one rebuild —
        whoever queues behind the lock finds the epoch already past its
        observation and returns immediately instead of bumping again."""
        async with self._recover_lock:
            if observed_epoch is not None and self.epoch > observed_epoch:
                return []  # another caller already rebuilt the chain
            timeout = self.load_timeout if timeout is None else timeout
            # pick a replacement for EVERY dead stage before committing
            # any of them: a no-replacement raise must leave stage_peers
            # untouched, not half-pointing at a never-loaded peer
            new_peers = list(self.stage_peers)
            replaced: list[tuple[int, str]] = []
            exclude = set(self.stage_peers) | {self.node.peer_id}
            for h in self.stage_health():
                if h["status"] == "online":
                    continue
                pid = self._pick_replacement(exclude)
                if pid is None:
                    # the ONLY raise carrying stage= — generate()'s retry
                    # loop keys "terminal, fail fast" off that
                    raise StageDead(
                        f"stage {h['stage']} ({h['peer']}) is {h['status']} "
                        "and no replacement peer is available",
                        peer=h["peer"], stage=h["stage"],
                    )
                new_peers[h["stage"]] = pid
                exclude.add(pid)
                replaced.append((h["stage"], pid))
            self.stage_peers = new_peers
            self.epoch += 1
            _C_RECOVERIES.inc()
            _C_EPOCH_BUMPS.inc()
            _C_STAGES_REPLACED.inc(len(replaced))
            await self._load_stages(timeout)
            if replaced:
                logger.info(
                    "pipeline failover: re-placed stages %s (epoch %d)",
                    replaced, self.epoch,
                )
            return replaced

    async def _chain(self, request_id: str, x: np.ndarray, offset: int) -> np.ndarray:
        """ids/hidden through every stage; returns last stage's logits.
        With relay chaining (load() dialed stage→stage links) the whole
        chain is one send + one receive at the coordinator."""
        fields = {"model": self.model, "request_id": request_id,
                  "offset": offset, "epoch": self.epoch}
        if self.relay_ok and len(self.stage_peers) > 1:
            result = await self.node.run_stage_task(
                self.stage_peers[0], protocol.TASK_PART_FORWARD_RELAY,
                fields, tensors={"x": x},
                # ONE await covers the whole chain (first prefill lazily
                # compiles every stage) — budget per stage, like the
                # per-stage path effectively did
                timeout=self.step_timeout * len(self.stage_peers),
                reply_from=self.stage_peers[-1],
            )
            return result["_tensors"]["out"]
        for peer in list(self.stage_peers):  # snapshot: replacement can rebind mid-chain
            result = await self.node.run_stage_task(
                peer, protocol.TASK_PART_FORWARD, fields, tensors={"x": x},
                timeout=self.step_timeout,
            )
            x = result["_tensors"]["out"]
        return x

    async def release(self, request_id: str, timeout: float | None = None) -> None:
        await asyncio.gather(
            *(
                self.node.run_stage_task(
                    peer,
                    "part_release",
                    {"model": self.model, "request_id": request_id},
                    timeout=self.step_timeout if timeout is None else timeout,
                )
                for peer in self.stage_peers
            ),
            return_exceptions=True,
        )

    async def generate(
        self,
        prompt_ids: list[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        eos_token_id: int | None = None,
        on_token=None,
        deadline_s: float | None = None,
    ) -> list[int]:
        """Greedy/temperature generation across the pipeline. Returns new
        token ids (stops at eos_token_id when given).

        Failover: a typed stage failure (StageDead/StageTimeout/
        StageError) triggers recover() — dead stages re-placed, chain
        rebuilt under a new epoch — and the generation RESUMES by
        re-prefilling prompt + accepted-so-far through the rebuilt chain
        (the coordinator holds every accepted token, so resume is exact
        for greedy decode). Bounded by max_failover_retries with
        exponential backoff under a wall-clock deadline: requests finish
        or fail fast with the typed error, never hang."""
        rid = new_id("ppreq")
        rng = np.random.default_rng(abs(hash(rid)) % (2**32))
        # left-truncate over-long prompts to what the stage caches can hold
        # (the engine's serving behavior: keep the most recent context)
        budget = self.max_seq_len - 1 - max(1, min(max_new_tokens, self.max_seq_len - 1))
        prompt_ids = list(prompt_ids)[-max(budget, 1):]
        n = len(prompt_ids)
        if n + max_new_tokens >= self.max_seq_len:
            max_new_tokens = max(0, self.max_seq_len - 1 - n)
        if max_new_tokens <= 0:
            return []
        deadline = self.clock.time() + (
            self.generation_deadline_s if deadline_s is None else deadline_s
        )
        out: list[int] = []
        # the root span of a pipeline generation: run_stage_task injects
        # its context into every stage task, so worker-side stage.task
        # spans across the mesh share this trace_id (stitched timeline)
        with get_tracer().span(
            "pipeline.generate", model=self.model,
            stages=len(self.stage_peers),
        ) as gen_span:
            try:
                return await self._generate_with_failover(
                    rid, prompt_ids, out, max_new_tokens,
                    temperature, eos_token_id, on_token, rng, deadline,
                )
            finally:
                gen_span.attrs["tokens"] = len(out)

    async def _generate_with_failover(
        self, rid, prompt_ids, out, max_new_tokens, temperature,
        eos_token_id, on_token, rng, deadline,
    ) -> list[int]:
        attempt = 0
        resume_in_place = False
        try:
            while True:
                # the epoch this attempt's chains run under: if a failure
                # lands after ANOTHER caller already rebuilt the chain,
                # recover() sees epoch > observed and coalesces to a no-op
                attempt_epoch = self.epoch
                try:
                    return await self._generate_attempt(
                        rid, prompt_ids, out, max_new_tokens, temperature,
                        eos_token_id, on_token, rng,
                        resume_in_place=resume_in_place,
                    )
                except StageError as e:
                    attempt += 1
                    remaining = deadline - self.clock.time()
                    # migration-preferring rung: an ALIVE chain (typed
                    # timeout/error, no re-placement happened, tokens
                    # accepted) keeps every stage's KV — resume decode in
                    # place. One try per generation: a second failure
                    # escalates to the release+recover+re-prefill rung
                    # (and StageDead skips straight there — a dead
                    # stage's cache is unrecoverable state).
                    resume_in_place = (
                        bool(out)
                        and not isinstance(e, StageDead)
                        and self.epoch == attempt_epoch
                        and attempt == 1
                        and attempt <= self.max_failover_retries
                        and remaining > 0
                    )
                    # flight-recorder incident BEFORE the terminal check:
                    # both a failover and a final failure leave a bundle.
                    # We're inside the pipeline.generate span, so the
                    # recorder snapshots this generation's stitched trace
                    # (every stage.task span shares its trace_id).
                    get_recorder().incident(
                        "stage_failover",
                        detail=f"{type(e).__name__}: {e}",
                        extra={
                            "attempt": attempt,
                            "accepted_tokens": len(out),
                            "model": self.model,
                            "epoch": attempt_epoch,
                            "resume_in_place": resume_in_place,
                            "terminal": attempt > self.max_failover_retries
                            or remaining <= 0,
                        },
                    )
                    if attempt > self.max_failover_retries or remaining <= 0:
                        raise
                    logger.warning(
                        "pipeline generation hit %s (%s); failover attempt "
                        "%d/%d with %d tokens accepted%s",
                        type(e).__name__, e, attempt,
                        self.max_failover_retries, len(out),
                        " (resuming in place)" if resume_in_place else "",
                    )
                    await self.clock.sleep(min(
                        self.failover_backoff_s * 2 ** (attempt - 1),
                        max(remaining, 0.0),
                    ))
                    if resume_in_place:
                        # re-check AFTER the sleep: a concurrent failover
                        # may have rebuilt the chain meanwhile — this
                        # rid's stage caches are gone on replacements, so
                        # an in-place resume would decode over garbage
                        if self.epoch != attempt_epoch:
                            resume_in_place = False
                        else:
                            _C_RESUMES_IN_PLACE.inc()
                            continue  # same rid: stage caches stay live
                    # every recovery step is capped by the REMAINING
                    # deadline budget: a wedged stage that also swallows
                    # release/part_load must not stretch time-to-failure
                    # past generation_deadline_s
                    budget = max(deadline - self.clock.time(), 1.0)
                    await self.release(  # survivors drop the old caches
                        rid, timeout=min(self.step_timeout, budget)
                    )
                    try:
                        await self.recover(
                            timeout=min(self.load_timeout,
                                        max(deadline - self.clock.time(), 1.0)),
                            observed_epoch=attempt_epoch,
                        )
                    except StageDead as rec_err:
                        if rec_err.stage is not None:
                            raise  # no replacement exists: terminal
                        # transient rebuild failure (e.g. the picked
                        # replacement died mid-load): spend the retry,
                        # the next recover() can pick another peer
                        logger.warning("recover attempt failed: %s", rec_err)
                    except StageError as rec_err:
                        logger.warning("recover attempt failed: %s", rec_err)
                    rid = new_id("ppreq")  # fresh caches on the rebuilt chain
        finally:
            await self.release(rid)

    async def _generate_attempt(
        self, rid, prompt_ids, out, max_new_tokens, temperature,
        eos_token_id, on_token, rng, resume_in_place: bool = False,
    ) -> list[int]:
        """One pass of the decode loop. `out` accumulates ACROSS attempts:
        on resume, prompt + accepted tokens re-prefill in one chain call
        and decode continues from where the failure struck.

        ``resume_in_place`` (alive-chain failover): skip the prefill —
        the stage caches under this SAME rid already hold K/V for every
        position below the frontier. Re-chaining the last accepted token
        at its own offset rewrites at most one position with identical
        values (idempotent) and yields the next sample; positions a
        half-finished step wrote past the frontier are overwritten or
        causally masked exactly like bucketed-prefill padding."""
        full = list(prompt_ids) + out
        n = len(full)
        if resume_in_place and out:
            logits = await self._chain(
                rid, np.asarray([[full[-1]]], np.int32), offset=n - 1
            )
            tok = self._sample(logits[0, -1], temperature, rng)
            return await self._decode_loop(
                rid, out, max_new_tokens, temperature, eos_token_id,
                on_token, rng, tok, offset=n,
            )
        # pow2 prompt bucket bounds worker recompiles; pad K/V past n is
        # overwritten by decode exactly when it enters the causal window
        # (same trick as the engine's bucketed prefill)
        bucket = 16
        while bucket < n:
            bucket *= 2
        bucket = min(bucket, self.max_seq_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :n] = full
        logits = await self._chain(rid, padded, offset=0)
        tok = self._sample(logits[0, n - 1], temperature, rng)
        greedy = temperature is None or temperature <= 0.0
        if (self.ring_ok and max_new_tokens - len(out) > 1
                and (greedy or self.ring_sampling_ok)):
            # sampled requests ride the burst path too: the LAST stage
            # draws with an rng keyed on (seed, position), so K tokens
            # still cost one coordinator round trip (r4 was greedy-only).
            # Gated on ring_sampling_ok — an older stage would ignore
            # the temperature/seed fields and silently argmax
            return await self._generate_ring(
                rid, tok, n, max_new_tokens, eos_token_id, on_token, out,
                temperature=temperature,
                seed=int(rng.integers(2**31)),
            )
        return await self._decode_loop(
            rid, out, max_new_tokens, temperature, eos_token_id, on_token,
            rng, tok, offset=n,
        )

    async def _decode_loop(
        self, rid, out, max_new_tokens, temperature, eos_token_id,
        on_token, rng, tok, offset: int,
    ) -> list[int]:
        """The per-token chain loop, shared by the fresh-prefill and
        resume-in-place entries (tok = next unchained sample, offset =
        the cache position its K/V will occupy)."""
        while True:
            if eos_token_id is not None and tok == eos_token_id:
                break
            out.append(tok)
            if on_token is not None:
                on_token(tok)
            if len(out) >= max_new_tokens:
                break
            logits = await self._chain(
                rid, np.asarray([[tok]], np.int32), offset=offset
            )
            offset += 1
            tok = self._sample(logits[0, -1], temperature, rng)
        return out

    async def train_step(
        self,
        input_ids: np.ndarray,  # [B, T] int32
        targets: np.ndarray,  # [B, T] int32 next-token labels
        lr: float = 1e-3,
        timeout: float = DEFAULT_STEP_TIMEOUT,
    ) -> float:
        """One cross-peer pipeline TRAINING step: forward through every
        stage (each retains its activations), softmax-cross-entropy grad
        at the coordinator, backward through the stages in reverse (each
        VJPs and SGD-updates its own params). Returns the mean loss.

        The reference's coordinator-worker training protocol
        (layer_forward_train / layer_backward, reference node.py:94-182)
        over real transformer stages — the cross-PEER counterpart of the
        in-slice GPipe trainer (parallel/pipeline.py).

        Caveat: tie_embeddings=True models hold the tied weight on BOTH
        the first and last stage (extract_stage_params), so cross-peer
        training updates the two copies with their partial gradients —
        effectively untying them. Train untied configs for exact parity
        with single-process training."""
        rid = new_id("pptrain")
        # first step compiles the stage forward AND the (bigger) VJP graph
        # — budget like load() does, not like a warm decode step
        step_timeout = max(timeout, 600.0)
        try:
            with get_tracer().span(
                "pipeline.train_step", model=self.model,
                stages=len(self.stage_peers), lr=lr,
            ):
                return await self._train_step_inner(rid, input_ids, targets,
                                                    lr, step_timeout)
        finally:
            # a failed/partial step must not strand retained activations
            # on the stages that DID run forward_train
            await self.release(rid)

    async def _train_step_inner(self, rid, input_ids, targets, lr, step_timeout):
        x = np.asarray(input_ids, np.int32)
        for peer in list(self.stage_peers):  # snapshot: replacement can rebind mid-chain
            result = await self.node.run_stage_task(
                peer, protocol.TASK_LAYER_FORWARD_TRAIN,
                {"model": self.model, "request_id": rid},
                tensors={"x": x}, timeout=step_timeout,
            )
            x = result["_tensors"]["out"]
        logits = x.astype(np.float64)  # [B, T, V]
        B, T, V = logits.shape
        z = logits - logits.max(axis=-1, keepdims=True)
        p = np.exp(z)
        p /= p.sum(axis=-1, keepdims=True)
        tgt = np.asarray(targets, np.int64).reshape(-1)
        n = B * T
        flat = p.reshape(n, V)
        loss = float(-np.log(
            np.maximum(flat[np.arange(n), tgt], 1e-30)
        ).mean())
        # grad in place: softmax minus one at the target index (no
        # [n, V] one-hot materialization)
        dlogits = flat.astype(np.float32)
        dlogits[np.arange(n), tgt] -= 1.0
        dlogits /= n
        dy = dlogits.reshape(B, T, V)
        for peer in reversed(self.stage_peers):
            result = await self.node.run_stage_task(
                peer, protocol.TASK_LAYER_BACKWARD,
                {"model": self.model, "request_id": rid, "lr": lr},
                tensors={"dy": dy}, timeout=step_timeout,
            )
            tens = result.get("_tensors") or {}
            if "dx" in tens:
                dy = tens["dx"]
        return loss

    async def _generate_ring(
        self, rid, first_tok, n, max_new_tokens, eos_token_id, on_token, out,
        temperature: float = 0.0, seed: int = 0,
    ) -> list[int]:
        """Decode in ring bursts: one coordinator round trip per K tokens
        — tokens circulate stage0→…→last→stage0 with last-stage sampling
        (TASK_DECODE_RUN: argmax, or a (seed, position)-keyed softmax draw
        for temperature>0). The caller's finally releases the stage
        caches."""
        if eos_token_id is not None and first_tok == eos_token_id:
            return out
        out.append(first_tok)
        if on_token is not None:
            on_token(first_tok)
        tok, offset = first_tok, n  # position tok's K/V takes when fed
        while len(out) < max_new_tokens:
            k = min(self.ring_burst, max_new_tokens - len(out))
            result = await self.node.run_stage_task(
                self.stage_peers[0],
                protocol.TASK_DECODE_RUN,
                {
                    "model": self.model, "request_id": rid,
                    "token": int(tok), "offset": int(offset), "k": int(k),
                    "eos": eos_token_id,
                    "temperature": float(temperature or 0.0),
                    "seed": int(seed),
                    "epoch": self.epoch,
                },
                timeout=self.step_timeout + 2.0 * k,
                reply_from=self.stage_peers[-1],
            )
            toks = result.get("tokens") or []
            for t in toks:
                out.append(t)
                if on_token is not None:
                    on_token(t)
            if result.get("stopped") or not toks:
                break
            # fed this burst: tok + toks[:-1]; toks[-1] feeds next burst
            offset += len(toks)
            tok = toks[-1]
        return out

    @staticmethod
    def _sample(logits: np.ndarray, temperature: float, rng) -> int:
        if temperature is None or temperature <= 0.0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / max(temperature, 1e-6)
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        return int(rng.choice(len(p), p=p))

    def _stage_telemetry(self) -> tuple[list, list]:
        """(per-stage task-time p50s, hop RTTs) for the auto-depth
        heuristic: task timings come from the stage peers' gossiped
        digests (health.HealthStore), hop latency from the node's ping
        bookkeeping. Missing readings are simply absent — the heuristic
        degrades to the binary guess."""
        store = getattr(self.node, "health", None)
        fresh = store.fresh() if store is not None else {}
        timings = []
        for pid in self.stage_peers:
            hist = ((fresh.get(pid) or {}).get("hist") or {}).get(
                "pipeline.stage_task_ms"
            ) or {}
            p50 = hist.get("p50")
            if p50:
                timings.append(float(p50))
        rtts = [
            (self.node.peers.get(pid) or {}).get("rtt_ms")
            for pid in self.stage_peers
        ]
        return timings, [float(r) for r in rtts if r]

    def session(
        self,
        max_batch: int = 8,
        n_microbatches: int | str = "auto",
        interleave: bool = True,
        inflight_window: int | None = None,
    ) -> "PipelineSession":
        """A continuous-batching session over this coordinator's stages.
        n_microbatches="auto" resolves from the stage topology plus the
        gossiped stage-task timings (resolve_microbatches): 1 on a shared
        host, else a compute-vs-hop depth (legacy 2 without telemetry)."""
        if n_microbatches in (None, "auto"):
            addrs = [
                (self.node.peers.get(pid) or {}).get("addr")
                for pid in self.stage_peers
            ]
            try:
                timings, rtts = self._stage_telemetry()
            except Exception:  # noqa: BLE001 — telemetry is advisory
                timings, rtts = [], []
            n_microbatches = resolve_microbatches(
                addrs, stage_task_ms=timings, hop_rtt_ms=rtts
            )
        return PipelineSession(
            self.node,
            self.model,
            list(self.stage_peers),
            max_batch=max_batch,
            max_seq_len=self.max_seq_len,
            dtype=self.dtype,
            n_microbatches=n_microbatches,
            relay=self.relay_ok,
            coordinator=self,  # stage failover: recover + resume rows
            step_timeout=self.step_timeout,
            # the session inherits this coordinator's failover policy —
            # max_failover_retries=0 really disables failover everywhere
            max_failovers=self.max_failover_retries,
            failover_backoff_s=self.failover_backoff_s,
            interleave=interleave,
            inflight_window=inflight_window,
        )


# ------------------------------------------------------- batched session


class _SessionReq:
    """One request inside a PipelineSession (coordinator-side row state)."""

    __slots__ = (
        "ids", "out", "n", "max_new_tokens", "temperature", "eos", "rng",
        "on_token", "future", "last_tok",
    )

    def __init__(self, ids, max_new_tokens, temperature, eos, on_token):
        self.ids = ids
        self.out: list[int] = []
        self.n = len(ids)
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.eos = eos
        self.on_token = on_token
        self.rng = np.random.default_rng(abs(hash(tuple(ids[:8]))) % (2**32))
        self.future: asyncio.Future = asyncio.get_running_loop().create_future()
        self.last_tok = 0


class _Group:
    """One microbatch group: a fixed-size row table backed by its OWN
    per-stage KV cache (request_id = ``rid``) and, under the interleaved
    scheduler, its own free-running decode task. ``len()``/iteration
    expose the row table, so callers can treat a group as its rows."""

    __slots__ = ("idx", "rows", "rid", "queue", "wake", "task",
                 "failovers", "tokens", "prefills", "reprefills", "chains")

    def __init__(self, idx: int, size: int, rid: str):
        self.idx = idx
        self.rows: list[_SessionReq | None] = [None] * size
        self.rid = rid
        self.queue: deque[_SessionReq] = deque()
        self.wake = asyncio.Event()
        self.task: asyncio.Task | None = None
        self.failovers = 0  # consecutive; reset by a successful step
        # per-group progress counters: the straggler-isolation and
        # group-scoped failover tests pin behavior on these, never on
        # racy wall-clock thresholds
        self.tokens = 0
        self.prefills = 0  # admission chains run (incl. retried admissions)
        self.reprefills = 0  # admissions of rows that already held accepted
        # tokens — the failover re-prefill cost ("zero re-prefills" pins)
        self.chains = 0

    def __len__(self) -> int:
        return len(self.rows)

    def __iter__(self):
        return iter(self.rows)

    def active(self) -> list[int]:
        return [i for i, r in enumerate(self.rows) if r is not None]

    def free_row(self) -> int | None:
        for i, r in enumerate(self.rows):
            if r is None:
                return i
        return None

    def load(self) -> int:
        """Rows this group is responsible for (admitted + queued) — the
        admission-spread key."""
        return len(self.active()) + len(self.queue)


class PipelineSession:
    """Continuous-batching decode across pipeline stages.

    The unbatched PipelineCoordinator.generate pays a full
    coordinator→stage0→…→coordinator round trip PER TOKEN PER REQUEST —
    n_requests × n_tokens × n_stages wire hops. This session keeps ONE
    [B]-row KV cache per microbatch group per stage and drives all of a
    group's active rows through a single [B, 1] chain per decode step:
    the wire cost per step is n_stages hops REGARDLESS of how many
    requests ride in the batch — the cross-peer realization of the
    engine's continuous-batching scheduler (engine/scheduler.py).

    Mechanics:
    - admission: a new request joins the least-loaded group's queue and
      prefills into a free row with write_mask=[row] (stage caches update
      only that row) and gather=[n_i - 1] so the last stage returns
      [B, V], not the full [B, bucket, V] logits.
    - decode: x = last tokens [B, 1], per-row offsets [B], write_mask =
      active rows, gather = 0 → one chain, one sample per active row.
    - retirement: EOS / budget resolves the row's future and frees the
      row between steps; stale K/V from a previous occupant is never
      attended (positions ≥ the new row's offset sit outside the causal
      mask until decode overwrites them — the bucketed-prefill argument).

    **Interleaved scheduling (ISSUE 10, the default).** Each microbatch
    group owns an independent, free-running decode task: the moment group
    g's chain leaves stage 0, group g+1's chain can enter it — no
    per-step barrier, so a straggler group (or a long admission prefill,
    which is just another chain in that group's stream) never stalls the
    other groups' token emission. In-flight chains across groups are
    bounded by a sliding window (``inflight_window``, an asyncio
    semaphore): each group holds at most one slot at a time, so any
    window > 1 preserves straggler isolation while capping how much
    concurrent work the coordinator can pile onto a stage (whose runner
    enforces its own ``max_concurrent_forwards``). The pre-interleave
    barrier loop survives as ``interleave=False`` — the A/B baseline the
    ``pipeline_interleave`` bench rung measures bubble fraction against.

    **Group-scoped failover.** A typed stage failure rides a per-group
    ladder (see ``_on_group_failure``): epoch adoption (a concurrent
    rebuild without re-placement keeps surviving stages' caches) →
    resume-in-place on the live caches → release + rotate THIS group's
    rid, recover() the chain, and requeue only this group's rows for
    re-prefill (prompt + accepted-so-far, exact resume for greedy).
    Healthy groups keep decoding through another group's failover; they
    are evacuated only when recover() actually RE-PLACED a stage, whose
    process death took every group's caches with it. Past the bounded
    attempts the failed group's rows fail with the typed error — other
    groups are untouched.

    `stats` counts chains/steps/prefills session-wide and each group
    carries its own tokens/prefills/chains progress counters
    (``group_progress()``), so tests can assert amortization and
    straggler isolation deterministically.
    """

    def __init__(
        self,
        node,
        model: str,
        stage_peers: list[str],
        max_batch: int = 8,
        max_seq_len: int = 2048,
        dtype: str = "bfloat16",
        n_microbatches: int = 1,
        relay: bool = False,  # stage→stage links up (coordinator.load)
        coordinator=None,  # enables failover: recover() + row resume
        step_timeout: float = DEFAULT_STEP_TIMEOUT,
        max_failovers: int = DEFAULT_FAILOVER_RETRIES,
        failover_backoff_s: float = 0.2,
        # cap on one recovery's part_load round; None = the coordinator's
        # load_timeout. The failed group (and every row queued on it)
        # blocks for at most this long per failover attempt.
        failover_load_timeout: float | None = None,
        # False: the pre-ISSUE-10 lockstep barrier loop (admission parks
        # decode; all groups advance behind one per-step gather) — kept
        # selectable as the bench baseline for the bubble measurement
        interleave: bool = True,
        # sliding window of concurrently in-flight chains across groups;
        # None = 2 per stage (each group occupies one slot per chain)
        inflight_window: int | None = None,
    ):
        self.node = node
        self.clock = getattr(node, "clock", None) or get_clock()
        self.model = model
        self.stage_peers = stage_peers
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len
        self.dtype = dtype
        self.relay = relay and len(stage_peers) > 1
        self.coordinator = coordinator
        self.step_timeout = step_timeout
        self.max_failovers = max_failovers
        self.failover_backoff_s = failover_backoff_s
        self.failover_load_timeout = failover_load_timeout
        self.epoch = getattr(coordinator, "epoch", 0)
        self.interleave = bool(interleave)
        self.sid = new_id("ppsess")
        M = max(1, min(n_microbatches, max_batch))
        base, extra = divmod(max_batch, M)
        sizes = [s for s in (base + (1 if m < extra else 0) for m in range(M))
                 if s > 0]
        self.groups: list[_Group] = [
            _Group(i, s, self.sid if len(sizes) == 1 else f"{self.sid}:m{i}")
            for i, s in enumerate(sizes)
        ]
        if inflight_window is None:
            # cover every group (so neither scheduler is throttled by
            # default — the lockstep baseline gathers all M chains per
            # step) with 2-per-stage as the floor
            inflight_window = max(2, 2 * len(stage_peers), len(self.groups))
        self.inflight_window = max(1, int(inflight_window))
        self._window = asyncio.Semaphore(self.inflight_window)
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None  # lockstep-mode driver
        self._closed = False
        self.stats = {
            "chains": 0, "steps": 0, "prefills": 0, "tokens": 0,
            "tasks_sent": 0,  # coordinator sends: chains x stages, or
            # chains x 1 under relay — the wire-cost metric tests assert
            "resumes_in_place": 0,  # alive-chain failovers that kept the
            # stage caches (no re-prefill) — the migration-preferred rung
            "reprefills": 0,  # failover re-prefills of rows that already
            # held accepted tokens (healthy groups must stay at zero
            # through another group's failover)
        }

    # ------------------------------------------------------------- public

    async def generate(
        self,
        prompt_ids: list[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
        eos_token_id: int | None = None,
        on_token=None,
    ) -> list[int]:
        if self._closed:
            raise RuntimeError("session closed")
        budget = self.max_seq_len - 1 - max(
            1, min(max_new_tokens, self.max_seq_len - 1)
        )
        prompt_ids = list(prompt_ids)[-max(budget, 1):]
        n = len(prompt_ids)
        if n + max_new_tokens >= self.max_seq_len:
            max_new_tokens = max(0, self.max_seq_len - 1 - n)
        if max_new_tokens <= 0:
            return []
        req = _SessionReq(prompt_ids, max_new_tokens, temperature,
                          eos_token_id, on_token)
        # admission spread: the least-loaded group takes the new row, so
        # microbatch caches fill evenly and overlap has groups to overlap
        g = min(self.groups, key=lambda gr: (gr.load(), gr.idx))
        g.queue.append(req)
        self._ensure_running()
        g.wake.set()
        self._wake.set()
        try:
            return await req.future
        except asyncio.CancelledError:
            # abandoned consumer: shrink the budget to what's already out
            # so the row retires at the next step instead of decoding the
            # rest of its budget into a dead future
            if req in g.queue:
                g.queue.remove(req)
            req.max_new_tokens = len(req.out)
            raise

    def group_progress(self) -> list[dict]:
        """Per-group progress counters (tokens emitted, prefills run,
        chains sent, rows live/queued) — the deterministic instrument for
        'a straggler group must not stall the others'."""
        return [
            {
                "group": g.idx, "tokens": g.tokens, "prefills": g.prefills,
                "reprefills": g.reprefills,
                "chains": g.chains, "active": len(g.active()),
                "queued": len(g.queue), "failovers": g.failovers,
            }
            for g in self.groups
        ]

    async def close(self) -> None:
        self._closed = True
        self._wake.set()
        for g in self.groups:
            g.wake.set()
        tasks = [
            t for t in [self._task, *(g.task for g in self.groups)]
            if t is not None and not t.done()
        ]
        if tasks:
            _done, pending = await asyncio.wait(tasks, timeout=10.0)
            for t in pending:
                t.cancel()
        # fail whatever was still in flight — an awaiting generate() must
        # see the close, not hang until the service-layer timeout
        err = RuntimeError("pipeline session closed")
        for g in self.groups:
            for i, req in enumerate(g.rows):
                if req is not None:
                    g.rows[i] = None
                    if not req.future.done():
                        req.future.set_exception(err)
            for req in g.queue:
                if not req.future.done():
                    req.future.set_exception(err)
            g.queue.clear()
        await self._release_all()

    # ------------------------------------------------------------ internal

    def _ensure_running(self) -> None:
        loop = asyncio.get_running_loop()
        if self.interleave:
            for g in self.groups:
                if g.task is None or g.task.done():
                    g.task = loop.create_task(self._group_loop(g))
                    g.task.add_done_callback(log_task_exception)
        elif self._task is None or self._task.done():
            self._task = loop.create_task(self._lockstep_loop())
            self._task.add_done_callback(log_task_exception)

    @property
    def _any_active(self) -> bool:
        return any(g.active() for g in self.groups)

    @property
    def _any_pending(self) -> bool:
        return any(g.queue for g in self.groups)

    async def _release_rid(self, rid: str) -> None:
        try:
            await asyncio.gather(
                *(
                    self.node.run_stage_task(
                        peer, "part_release",
                        {"model": self.model, "request_id": rid},
                        timeout=self.step_timeout,
                    )
                    for peer in self.stage_peers
                ),
                return_exceptions=True,
            )
        except Exception:  # noqa: BLE001 — release is best-effort
            pass

    async def _release_all(self) -> None:
        await asyncio.gather(
            *(self._release_rid(g.rid) for g in self.groups),
            return_exceptions=True,
        )

    async def _chain(self, g: _Group, x, offsets, mask, gather) -> np.ndarray:
        # the sliding window bounds chains concurrently in flight across
        # groups — each group holds at most one slot, so a straggler
        # parks one slot, never the scheduler
        async with self._window:
            self.stats["chains"] += 1
            g.chains += 1
            fields = {
                "model": self.model,
                "request_id": g.rid,
                "offset": [int(o) for o in offsets],
                "write_mask": [bool(m) for m in mask],
                "epoch": self.epoch,
            }
            if self.relay:
                # one send, one receive: stages hand hidden states to each
                # other; the LAST stage answers us (gather rides the
                # chain). Timeout budgets per stage — one await covers the
                # whole chain
                self.stats["tasks_sent"] += 1
                result = await self.node.run_stage_task(
                    self.stage_peers[0], protocol.TASK_PART_FORWARD_RELAY,
                    {**fields, "gather": [int(g_) for g_ in gather]},
                    tensors={"x": x},
                    timeout=self.step_timeout * len(self.stage_peers),
                    reply_from=self.stage_peers[-1],
                )
                return result["_tensors"]["out"]
            for peer in self.stage_peers[:-1]:
                self.stats["tasks_sent"] += 1  # meshlint: ignore[ML-R003] -- atomic counter bump: no read of stats spans an await
                result = await self.node.run_stage_task(
                    peer, protocol.TASK_PART_FORWARD, fields,
                    tensors={"x": x}, timeout=self.step_timeout,
                )
                x = result["_tensors"]["out"]
            self.stats["tasks_sent"] += 1
            result = await self.node.run_stage_task(
                self.stage_peers[-1],
                protocol.TASK_PART_FORWARD,
                {**fields, "gather": [int(g_) for g_ in gather]},
                tensors={"x": x},
                timeout=self.step_timeout,
            )
            return result["_tensors"]["out"]  # [B, V]

    async def _admit(self, g: _Group, row: int, req: _SessionReq) -> None:
        """Masked prefill of one request into `row` of group `g`'s cache.
        A row requeued by failover carries accepted tokens in req.out:
        prefilling prompt + accepted resumes its decode exactly where the
        failure struck (offsets in _step_group are n + len(out) already).
        Under the interleaved scheduler this chain is just another chunk
        in the group's stream — other groups keep decoding through it."""
        self.stats["prefills"] += 1
        g.prefills += 1
        if req.out:
            # a requeued row resuming by re-prefill (prompt + accepted) —
            # the cost the group-scoped ladder confines to the failed group
            self.stats["reprefills"] += 1
            g.reprefills += 1
        B = len(g.rows)
        full = list(req.ids) + req.out
        n_full = len(full)
        bucket = 16
        while bucket < n_full:
            bucket *= 2
        bucket = min(bucket, self.max_seq_len)
        x = np.zeros((B, bucket), np.int32)
        x[row, :n_full] = full
        offsets = np.zeros(B, np.int32)
        mask = np.zeros(B, bool)
        mask[row] = True
        gather = np.zeros(B, np.int32)
        gather[row] = n_full - 1
        logits = await self._chain(g, x, offsets, mask, gather)
        req.last_tok = PipelineCoordinator._sample(
            logits[row], req.temperature, req.rng
        )
        g.rows[row] = req

    def _accept(self, g: _Group, req: _SessionReq, tok: int) -> bool:
        """Book one sampled token for a row; False retires the row."""
        if req.eos is not None and tok == req.eos:
            return False
        req.out.append(tok)
        self.stats["tokens"] += 1
        g.tokens += 1
        if req.on_token is not None:
            try:
                req.on_token(tok)
            except Exception:  # noqa: BLE001 — consumer bug ≠ session bug
                logger.exception("on_token callback failed")
        return len(req.out) < req.max_new_tokens

    def _retire(self, g: _Group, row: int) -> None:
        req = g.rows[row]
        g.rows[row] = None
        if not req.future.done():
            req.future.set_result(req.out)

    async def _step_group(self, g: _Group) -> None:
        """One decode step over group g's active rows (one chain)."""
        active = g.active()
        self.stats["steps"] += 1
        with get_tracer().span(
            "pipeline.step", group=g.idx, rows=len(active),
            relay=self.relay, interleave=self.interleave,
        ):
            rows = g.rows
            B = len(rows)
            x = np.zeros((B, 1), np.int32)
            offsets = np.zeros(B, np.int32)
            mask = np.zeros(B, bool)
            for i in active:
                req = rows[i]
                x[i, 0] = req.last_tok
                offsets[i] = req.n + len(req.out)
                mask[i] = True
            logits = await self._chain(
                g, x, offsets, mask, np.zeros(B, np.int32)
            )
            # re-read the active set: another group's failover may have
            # evacuated these rows mid-chain (they'll re-prefill) — the
            # stale chain's result must not book tokens for them
            for i in g.active():
                req = rows[i]
                tok = req.last_tok
                if not self._accept(g, req, tok):
                    self._retire(g, i)
                    continue
                req.last_tok = PipelineCoordinator._sample(
                    logits[i], req.temperature, req.rng
                )

    # ------------------------------------------------------------ drivers

    def _claim_admission(self, g: _Group) -> _SessionReq | None:
        """Group g's next admission: its own queue first; with a free
        row and an empty queue, STEAL a fresh request from the longest
        other queue. Submit-time assignment is a load hint, not an
        affinity contract — a request must not sit head-of-line behind
        another group's long row while this group's slot idles.
        Failover-requeued rows (accepted tokens) are never stolen: their
        re-admission is imminent once their group's recovery completes,
        and stealing them would shift re-prefill accounting onto healthy
        groups."""
        while g.queue:
            req = g.queue.popleft()
            if not req.future.done():  # else: abandoned while queued
                return req
        for other in sorted(
            (o for o in self.groups if o is not g and o.queue),
            key=lambda o: -len(o.queue),
        ):
            for req in list(other.queue):
                if req.future.done():
                    other.queue.remove(req)
                    continue
                if not req.out:
                    other.queue.remove(req)
                    return req
        return None

    async def _drain_admissions(self, g: _Group) -> bool:
        """Admit queued requests into group g's free rows (each
        admission is one masked-prefill chain). Shared by both drivers
        so their admission semantics can never diverge. Returns False
        when an admission chain failed — the failure, with the in-flight
        request, has already been routed through the group-scoped
        ladder."""
        while True:
            row = g.free_row()
            if row is None:
                return True
            req = self._claim_admission(g)
            if req is None:
                return True
            try:
                await self._admit(g, row, req)
            except Exception as e:  # noqa: BLE001 — group-scoped ladder
                await self._on_group_failure(g, e, req)
                return False

    async def _group_loop(self, g: _Group) -> None:
        """The free-running driver of ONE microbatch group: admit queued
        requests into free rows (each admission is one masked-prefill
        chain) and chain decode steps back-to-back. No barrier against
        the other groups — the moment this group's chain leaves stage 0,
        another group's chain can enter it."""
        while not self._closed:
            if not g.queue and not g.active():
                g.wake.clear()
                try:
                    await self.clock.wait_for(g.wake.wait(), 30.0)
                except asyncio.TimeoutError:
                    # a generate() can land during wait_for's cancellation
                    # window (an await point) — park only when still idle
                    if g.queue or g.active():
                        continue
                    break  # idle: park; the next assignment restarts us
                continue
            try:
                if not await self._drain_admissions(g):
                    continue  # admission failure already rode the ladder
                if g.active():
                    await self._step_group(g)
                    g.failovers = 0  # a whole step landed: chain healthy
            except Exception as e:  # noqa: BLE001 — group-scoped ladder
                await self._on_group_failure(g, e, None)

    async def _lockstep_loop(self) -> None:
        """The pre-interleave barrier scheduler, kept selectable
        (``interleave=False``) as the A/B baseline the bench rung
        measures bubble fraction against: admission prefills park every
        group's decode, and all busy groups advance behind one per-step
        gather barrier — a straggler group stalls the rest for exactly
        the bubble time the free-running scheduler drains."""
        while not self._closed:
            if not self._any_pending and not self._any_active:
                self._wake.clear()
                try:
                    await self.clock.wait_for(self._wake.wait(), 30.0)
                except asyncio.TimeoutError:
                    if self._any_pending or self._any_active:
                        continue
                    break
                continue
            for g in list(self.groups):  # snapshot: admit() appends mid-drain
                await self._drain_admissions(g)
            busy = [g for g in self.groups if g.active()]
            if not busy:
                continue
            results = await asyncio.gather(
                *(self._step_group(g) for g in busy), return_exceptions=True
            )
            for g, r in zip(busy, results):
                if isinstance(r, BaseException):
                    await self._on_group_failure(g, r, None)
                else:
                    g.failovers = 0

    # ------------------------------------------------------------ failover

    async def _evacuate(self, g: _Group) -> list[_SessionReq]:
        """Pull group g's in-flight rows, release its stage caches, and
        rotate its rid (the next admission starts from fresh caches).
        Returns the pulled rows — callers requeue or fail them."""
        rows: list[_SessionReq] = []
        for i, req in enumerate(g.rows):
            if req is not None:
                g.rows[i] = None
                rows.append(req)
        old_rid = g.rid
        fresh = new_id("ppsess")
        if len(self.groups) == 1:
            # legacy contract: a single-group session's id IS its cache
            # identity, and callers observe it rotate on failover
            self.sid = fresh
            g.rid = fresh
        else:
            # multi-group: rotate only THIS group's rid — the session id
            # keeps naming the session, and sibling groups' rids (still
            # derived from it) stay live
            g.rid = f"{fresh}:m{g.idx}"
        await self._release_rid(old_rid)
        return rows

    async def _on_group_failure(self, g: _Group, e: Exception,
                                admitting: "_SessionReq | None") -> None:
        """A chain of group g failed. Group-scoped ladder (ISSUE 10):
        only THIS group's rows ride it — healthy groups' chains keep
        running through it. Rungs:

        1. epoch adoption: a concurrent recover() bumped the stage epoch
           WITHOUT re-placing any stage — surviving stages kept this
           group's caches, so adopt the epoch and retry in place (the
           error was bookkeeping, not a fault; no failover charged).
        2. resume in place: an ALIVE chain (typed error/timeout, epoch
           unchanged) keeps every stage's K/V — retry the step on the
           live caches, one try per failure burst.
        3. group failover: release + rotate THIS group's rid, recover()
           the chain (single-flight across groups via observed_epoch),
           requeue this group's rows for re-prefill (prompt + accepted).
           Only when recover() actually RE-PLACED a stage are the other
           groups evacuated too — the replaced process took every
           group's caches with it.
        4. typed failure of this group's rows; other groups untouched.
        """
        if (
            not self._closed
            and self.coordinator is not None
            and isinstance(e, StageError)
            and not isinstance(e, StageDead)
            and self.coordinator.epoch != self.epoch
            and list(self.coordinator.stage_peers) == list(self.stage_peers)
        ):
            self.epoch = self.coordinator.epoch
            self.relay = (self.coordinator.relay_ok
                          and len(self.stage_peers) > 1)
            if admitting is not None:
                g.queue.appendleft(admitting)
            logger.info(
                "group %d adopting rebuilt chain epoch %d (same stages — "
                "caches intact, no re-prefill)", g.idx, self.epoch,
            )
            return
        if (
            not self._closed
            and isinstance(e, StageError)
            and not isinstance(e, StageDead)
            and g.failovers == 0
            and self.max_failovers > 0
            and (self.coordinator is None
                 or self.coordinator.epoch == self.epoch)
        ):
            # one in-place try per failure burst (failovers resets on a
            # whole successful step); a repeat escalates to re-prefill
            g.failovers += 1
            await self.clock.sleep(self.failover_backoff_s)
            # re-check AFTER the sleep: a concurrent failover may have
            # rebuilt the chain meanwhile, invalidating this group's
            # stage caches on any replaced peer — fall through to the
            # requeue path then, bounded by the incremented count
            if (self.coordinator is None
                    or self.coordinator.epoch == self.epoch):
                if admitting is not None:
                    # the popped request never finished admission: its
                    # masked prefill re-runs against the same rid
                    # (idempotent row writes), resumed rows are untouched
                    g.queue.appendleft(admitting)
                self.stats["resumes_in_place"] = (
                    self.stats.get("resumes_in_place", 0) + 1
                )
                _C_RESUMES_IN_PLACE.inc()
                logger.warning(
                    "group %d step failed (%s: %s); resuming in place on "
                    "live stage caches", g.idx, type(e).__name__, e,
                )
                return
            logger.warning(
                "group %d step failed (%s: %s); chain rebuilt during "
                "backoff — requeueing rows instead of resuming in place",
                g.idx, type(e).__name__, e,
            )
        # the popped-but-not-yet-admitted request is in neither the queue
        # nor a row — collect it with the rest so it can't hang
        inflight: list[_SessionReq] = (
            [admitting] if admitting is not None else []
        )
        inflight.extend(await self._evacuate(g))
        if (not self._closed and self.coordinator is not None
                and isinstance(e, StageError)
                and g.failovers < self.max_failovers):
            g.failovers += 1
            _C_SESSION_FAILOVERS.inc()
            try:
                await self.clock.sleep(min(
                    self.failover_backoff_s * 2 ** (g.failovers - 1), 5.0
                ))
                # observed_epoch: if another group/generation already
                # rebuilt the chain, this returns [] and we just adopt
                replaced = await self.coordinator.recover(
                    timeout=self.failover_load_timeout,
                    observed_epoch=self.epoch,
                )
            except Exception as rec_err:  # noqa: BLE001 — typed fail below
                logger.warning("group %d failover failed: %s", g.idx, rec_err)
                if isinstance(rec_err, StageError):
                    e = rec_err
            else:
                topology_changed = (
                    list(self.coordinator.stage_peers)
                    != list(self.stage_peers)
                )
                self.stage_peers = list(self.coordinator.stage_peers)
                self.relay = (self.coordinator.relay_ok
                              and len(self.stage_peers) > 1)
                self.epoch = self.coordinator.epoch
                if replaced or topology_changed:
                    # a RE-PLACED stage lost every group's caches with
                    # its process: evacuate the healthy groups too (their
                    # rows requeue into their own groups and re-prefill)
                    for other in list(self.groups):  # snapshot: evacuation awaits per group
                        if other is g:
                            continue
                        other_rows = await self._evacuate(other)
                        live = [r for r in other_rows
                                if not r.future.done()]
                        other.queue.extendleft(reversed(live))
                        other.wake.set()
                live = [r for r in inflight if not r.future.done()]
                g.queue.extendleft(reversed(live))
                g.wake.set()
                self._wake.set()
                logger.info(
                    "group %d failover %d/%d: requeued %d rows (epoch "
                    "%d%s)", g.idx, g.failovers, self.max_failovers,
                    len(live), self.epoch,
                    ", all groups evacuated"
                    if replaced or topology_changed else "",
                )
                return
        logger.warning(
            "group %d step failed (%s: %s); failing %d in-flight rows",
            g.idx, type(e).__name__, e, len(inflight),
        )
        err = e if isinstance(e, StageError) else RuntimeError(
            f"pipeline session step failed: {e}"
        )
        for req in inflight:
            if not req.future.done():
                req.future.set_exception(err)
