"""Deterministic fault injection for the meshnet pipeline.

The chaos tests' original `_hard_kill` lived in tests/test_chaos.py;
failover needs the same process-death semantics PLUS per-stage, per-step
precision ("kill stage 1 on its 3rd forward"), so both live here as
product code — operators can drive game-day drills with the same
primitives the test suite uses (docs/ROBUSTNESS.md).

- `hard_kill(node)`: every socket dies, no GOODBYE, nothing keeps
  responding — what a power loss or OOM kill looks like to the mesh.
- `ChaosStage(node, action=..., at_step=N)`: intercepts the node's stage
  task handling and, at the Nth matching task, kills the node, delays
  the task, or black-holes it (and everything after — a wedged process
  that still holds its sockets open).
"""

from __future__ import annotations

import asyncio
import contextlib

from .. import protocol

# the stage-serving task kinds a ChaosStage counts as "steps"
FORWARD_KINDS = (
    protocol.TASK_PART_FORWARD,
    protocol.TASK_PART_FORWARD_RELAY,
    protocol.TASK_DECODE_RUN,
)


async def hard_kill(node) -> None:
    """Process-death semantics for an in-process node: every socket dies,
    no GOODBYE is sent, nothing of the node keeps responding."""
    node._stopped = True  # noqa: SLF001 — simulating death, not clean stop
    for info in list(node.peers.values()):
        with contextlib.suppress(Exception):
            await info["ws"].close()
    if node._server is not None:
        node._server.close()
        await node._server.wait_closed()
    for t in list(node._tasks):
        t.cancel()


class ChaosStage:
    """Wrap one stage worker node's task handler with a scheduled fault.

    action:
      - "kill":      hard_kill the node at step `at_step`; the triggering
                     task (and everything after) is dropped.
      - "blackhole": silently drop every matching task from `at_step` on
                     — the node stays connected but never answers, which
                     is the StageTimeout path.
      - "delay":     sleep `delay_s` before handling each matching task
                     from `at_step` on (latency injection).

    Steps count tasks whose kind is in `kinds` (default: the forward /
    relay / ring-decode serving kinds). `triggered` is an asyncio.Event
    tests can await for deterministic sequencing; `steps_seen` exposes
    the count. `restore()` un-wraps the handler (no-op after "kill").
    """

    def __init__(
        self,
        node,
        action: str = "kill",
        at_step: int = 1,
        delay_s: float = 1.0,
        kinds: tuple[str, ...] = FORWARD_KINDS,
    ):
        if action not in ("kill", "blackhole", "delay"):
            raise ValueError(f"unknown chaos action {action!r}")
        self.node = node
        self.action = action
        self.at_step = int(at_step)
        self.delay_s = float(delay_s)
        self.kinds = tuple(kinds)
        self.steps_seen = 0
        self.triggered = asyncio.Event()
        self._orig = node._handle_task
        node._handle_task = self._handle_task

    async def _handle_task(self, ws, data):
        if data.get("kind") in self.kinds:
            self.steps_seen += 1
            if self.steps_seen >= self.at_step:
                if self.action == "kill":
                    if not self.triggered.is_set():
                        self.triggered.set()
                        await hard_kill(self.node)
                    return  # the dead never answer
                if self.action == "blackhole":
                    self.triggered.set()
                    return  # connected but mute: the timeout path
                self.triggered.set()
                await asyncio.sleep(self.delay_s)
        await self._orig(ws, data)

    def restore(self) -> None:
        self.node._handle_task = self._orig
