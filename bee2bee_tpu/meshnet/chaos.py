"""Deterministic fault injection for the meshnet pipeline.

The chaos tests' original `_hard_kill` lived in tests/test_chaos.py;
failover needs the same process-death semantics PLUS per-stage, per-step
precision ("kill stage 1 on its 3rd forward"), so both live here as
product code — operators can drive game-day drills with the same
primitives the test suite uses (docs/ROBUSTNESS.md).

- `hard_kill(node)`: every socket dies, no GOODBYE, nothing keeps
  responding — what a power loss or OOM kill looks like to the mesh.
- `ChaosStage(node, action=..., at_step=N)`: intercepts the node's stage
  task handling and, at the Nth matching task, kills the node, delays
  the task, or black-holes it (and everything after — a wedged process
  that still holds its sockets open).
"""

from __future__ import annotations

import asyncio
import contextlib

from .. import protocol

# the stage-serving task kinds a ChaosStage counts as "steps"
FORWARD_KINDS = (
    protocol.TASK_PART_FORWARD,
    protocol.TASK_PART_FORWARD_RELAY,
    protocol.TASK_DECODE_RUN,
)


async def hard_kill(node) -> None:
    """Process-death semantics for an in-process node: every socket dies,
    no GOODBYE is sent, nothing of the node keeps responding."""
    node._stopped = True  # noqa: SLF001 — simulating death, not clean stop
    for info in list(node.peers.values()):
        with contextlib.suppress(Exception):
            await info["ws"].close()
    if node._server is not None:
        node._server.close()
        await node._server.wait_closed()
    for t in list(node._tasks):
        t.cancel()


class ChaosMigration:
    """Deterministic fault injection for live generation migration
    (meshnet/migrate.py). The satellite contract: every faulted path
    degrades down the fallback ladder (KV → re-prefill → typed error)
    with a ``migration:<reason>`` incident bundle, never a hung
    generation.

    action:
      - "kill_link":      close the source→target connection after
                          ``at_chunk`` KV_BLOCKS frames left (mid-stream
                          transport death: the source's ladder re-prefills
                          on another peer; the target abandons its partial
                          import on the drop).
      - "kill_source":    hard_kill the whole SOURCE node at that point
                          (process death: nothing falls back — the target
                          must still clean up, nothing may hang).
      - "corrupt_piece":  flip a payload byte of chunk ``at_chunk`` so its
                          sha256 fails at the target (typed hash_mismatch
                          reject → re-prefill fallback).
      - "exhaust_target": wrap the TARGET node's engine schedulers so the
                          next KV import raises pool-exhausted (typed
                          reject → re-prefill fallback elsewhere).

    ``triggered`` is an asyncio.Event for deterministic sequencing;
    ``restore()`` unwraps everything (no-op after "kill_source").
    """

    def __init__(self, node, action: str = "kill_link", at_chunk: int = 0):
        if action not in (
            "kill_link", "kill_source", "corrupt_piece", "exhaust_target"
        ):
            raise ValueError(f"unknown chaos action {action!r}")
        self.node = node
        self.action = action
        self.at_chunk = int(at_chunk)
        self.triggered = asyncio.Event()
        self._restores: list = []
        if action in ("kill_link", "kill_source", "corrupt_piece"):
            mgr = node.migration
            orig = mgr._send_chunk

            async def wrapped(ws, frame: bytes, seq: int):
                if seq >= self.at_chunk and action == "kill_source":
                    if not self.triggered.is_set():
                        self.triggered.set()
                        await hard_kill(node)
                    raise ConnectionError("chaos: source killed mid-stream")
                if seq >= self.at_chunk and action == "kill_link":
                    self.triggered.set()
                    with contextlib.suppress(Exception):
                        await ws.close()
                    raise ConnectionError("chaos: link dropped mid-stream")
                if seq == self.at_chunk and action == "corrupt_piece":
                    self.triggered.set()
                    frame = frame[:-1] + bytes([frame[-1] ^ 0xFF])
                await orig(ws, frame, seq)

            mgr._send_chunk = wrapped
            self._restores.append(lambda: setattr(mgr, "_send_chunk", orig))
        else:  # exhaust_target
            from ..engine.scheduler import _PoolExhausted

            # the wrapper below runs on the ENGINE SCHEDULER THREAD;
            # asyncio.Event.set is not thread-safe, so the trigger hops
            # back onto the loop that owns the event
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:  # constructed outside a loop (sync test)
                loop = None

            for svc in node.local_services.values():
                eng = getattr(svc, "engine", None)
                sch = getattr(eng, "scheduler", None) if eng else None
                if sch is None:
                    continue
                orig_import = sch._paged_import

                def failing(req, b, st, _sch=sch, _orig=orig_import):
                    if loop is not None:
                        loop.call_soon_threadsafe(self.triggered.set)
                    else:
                        self.triggered.set()
                    raise _PoolExhausted("chaos: import pool exhausted")

                sch._paged_import = failing
                self._restores.append(
                    lambda _sch=sch, _orig=orig_import: setattr(
                        _sch, "_paged_import", _orig
                    )
                )

    def restore(self) -> None:
        for undo in self._restores:
            undo()
        self._restores.clear()


class ChaosController:
    """Deterministic fault injection for the elastic fleet control loop
    (fleet/controller.py). The tentpole contract (tests/test_fleet.py):
    a controller death or network split never strands a draining node,
    a half-provisioned replica never receives traffic, and no in-flight
    generation is dropped.

    Faults:
      - ``await kill_leader()``: hard_kill the node currently holding
        the lease (mid-drain if the test timed it so) — the successor's
        orphan scan must adopt or roll back whatever it left behind.
      - ``partition(a, b, ops=None)``: drop frames between nodes a and b
        in BOTH directions (default: only the fleet ops — lease gossip
        and actions — the nastier case where telemetry still flows but
        leadership is invisible). ``heal()`` restores delivery.
      - ``await usurp(node, epoch=None)``: force ``node`` to claim the
        lease NOW (default: at the current highest epoch — a true
        split-brain tie). Both leaders broadcast; the deterministic
        ordering (higher epoch, then smaller peer id) must leave exactly
        one standing.
      - ``fail_probe(node, fails=1)``: the next ``fails`` warm-up probes
        on that controller report failure — the provision-probe chaos
        rung (replica must be rolled back to standby, never eligible).

    ``restore()`` undoes partitions and probe wraps (kills stay dead).
    """

    def __init__(self, nodes=()):
        self.nodes = list(nodes)
        self._restores: list = []

    # ------------------------------------------------------------- leaders

    def leader(self):
        """The node currently believing it holds the lease (None if no
        node does; tests settle on exactly one)."""
        leaders = [n for n in self.nodes if n.fleet.is_leader and not n._stopped]
        return leaders[0] if leaders else None

    def leaders(self):
        return [n for n in self.nodes if n.fleet.is_leader and not n._stopped]

    async def kill_leader(self):
        """Process-death semantics for the current leader; returns the
        killed node (its in-flight action dies with it)."""
        node = self.leader()
        if node is None:
            raise AssertionError("no leader to kill")
        await hard_kill(node)
        return node

    # ---------------------------------------------------------- partitions

    def partition(self, a, b, ops: tuple[str, ...] | None = None) -> None:
        """Drop `ops` frames (default: the fleet control plane) between
        nodes a and b, both directions, at the RECEIVER — the sender
        still believes it spoke, exactly like a one-way-lossy network."""
        drop_ops = ops or (
            protocol.FLEET_LEASE, protocol.FLEET_ACTION, protocol.FLEET_ACK
        )
        for me, other in ((a, b), (b, a)):
            orig = me._on_message
            other_id = other.peer_id

            async def filtered(ws, data, _me=me, _orig=orig,
                               _other=other_id):
                if data.get("type") in drop_ops:
                    pid = await _me._peer_for(ws)
                    if pid == _other:
                        return  # dropped on the virtual wire
                await _orig(ws, data)

            me._on_message = filtered
            self._restores.append(
                lambda _me=me, _orig=orig: setattr(_me, "_on_message", _orig)
            )

    def heal(self) -> None:
        """Restore every partition/probe wrap installed so far."""
        self.restore()

    # ----------------------------------------------------------- usurpation

    async def usurp(self, node, epoch: int | None = None):
        """Force `node`'s controller to claim leadership immediately —
        bypassing the lapse wait — and broadcast the claim. With the
        default epoch (the highest seen) this manufactures a genuine
        double-leader split-brain whose resolution must be deterministic."""
        ctrl = node.fleet
        ctrl.epoch = int(epoch) if epoch is not None else max(
            1, ctrl.lease.highest_epoch
        )
        ctrl.is_leader = True
        await ctrl._broadcast_lease()
        return ctrl

    # --------------------------------------------------------------- probes

    def fail_probe(self, node, fails: int = 1) -> None:
        """Make the next `fails` warm-up probes on this controller fail
        (the replica must end back in standby, never eligible)."""
        prov = node.fleet.provisioner
        orig = prov.probe
        state = {"left": int(fails)}

        async def failing(target, _orig=orig, _state=state):
            if _state["left"] > 0:
                _state["left"] -= 1
                return False, "chaos: probe failure injected"
            return await _orig(target)

        prov.probe = failing
        self._restores.append(
            lambda _prov=prov, _orig=orig: setattr(_prov, "probe", _orig)
        )

    def restore(self) -> None:
        # reversed: stacked wraps on one node (two partitions, repeated
        # fail_probe) must unwind inner-first, or an outer restore would
        # re-install the inner wrapper it captured as "original"
        for undo in reversed(self._restores):
            undo()
        self._restores.clear()


class ChaosStage:
    """Wrap one stage worker node's task handler with a scheduled fault.

    action:
      - "kill":      hard_kill the node at step `at_step`; the triggering
                     task (and everything after) is dropped.
      - "blackhole": silently drop every matching task from `at_step` on
                     — the node stays connected but never answers, which
                     is the StageTimeout path.
      - "delay":     sleep `delay_s` before handling each matching task
                     from `at_step` on (latency injection).
      - "error":     answer every matching task from `at_step` on with a
                     typed TASK_ERROR instead of running it — the
                     StageError path (the node stays alive and serves
                     everything the fault does NOT match).

    Steps count tasks whose kind is in `kinds` (default: the forward /
    relay / ring-decode serving kinds) AND that pass `match` (an optional
    ``match(data) -> bool`` predicate — e.g. scope the fault to ONE
    microbatch group's request_id, which is how the group-scoped failover
    tests fail one group's chain while the others keep decoding).
    `triggered` is an asyncio.Event tests can await for deterministic
    sequencing; `steps_seen` exposes the count. `restore()` un-wraps the
    handler (no-op after "kill").
    """

    def __init__(
        self,
        node,
        action: str = "kill",
        at_step: int = 1,
        delay_s: float = 1.0,
        kinds: tuple[str, ...] = FORWARD_KINDS,
        match=None,  # optional predicate over the task frame dict
    ):
        if action not in ("kill", "blackhole", "delay", "error"):
            raise ValueError(f"unknown chaos action {action!r}")
        self.node = node
        self.action = action
        self.at_step = int(at_step)
        self.delay_s = float(delay_s)
        self.kinds = tuple(kinds)
        self.match = match
        self.steps_seen = 0
        self.triggered = asyncio.Event()
        self._orig = node._handle_task
        node._handle_task = self._handle_task

    async def _answer_error(self, ws, data) -> None:
        """Route a typed TASK_ERROR the way a real failed task would: a
        relayed task reports to the ORIGIN coordinator (which is the peer
        awaiting the reply), a first-hop task answers the sender."""
        origin = data.get("origin_peer")
        task_id = data.get("origin_task_id") if origin else data.get("task_id")
        reply_ws = ws
        if origin:
            info = self.node.peers.get(origin)
            if info is None:
                return  # origin gone: nothing awaits the reply
            reply_ws = info["ws"]
        await self.node._send(reply_ws, protocol.msg(
            protocol.TASK_ERROR, task_id=task_id,
            error="chaos: injected stage error",
            error_kind=protocol.ERR_KIND_ERROR,
        ))

    async def _handle_task(self, ws, data):
        if data.get("kind") in self.kinds and (
            self.match is None or self.match(data)
        ):
            self.steps_seen += 1
            if self.steps_seen >= self.at_step:
                if self.action == "kill":
                    if not self.triggered.is_set():
                        self.triggered.set()
                        await hard_kill(self.node)
                    return  # the dead never answer
                if self.action == "blackhole":
                    self.triggered.set()
                    return  # connected but mute: the timeout path
                if self.action == "error":
                    self.triggered.set()
                    await self._answer_error(ws, data)
                    return
                self.triggered.set()
                await self.node.clock.sleep(self.delay_s)
        await self._orig(ws, data)

    def restore(self) -> None:
        self.node._handle_task = self._orig
