"""Mesh weight distribution: DHT announce → piece fetch → serve.

This wires the three previously separate mechanisms into the end-to-end
flow the reference only sketched (reference dht.py:53-64 announce/lookup +
pieces.py:7-32 chunking + p2p_runtime.py:675-683 stub handlers):

- A serving node **publishes**: its params are sharded into content-
  addressed pieces (pieces.build_shard_manifest, using the SAME partition
  rules the engine's jit shardings use), the blobs enter the node's piece
  store, and the manifest + per-piece provider records go onto the DHT.
- A joining peer **fetches**: manifest from the DHT → the pieces its mesh
  coordinates need (ShardManifest.pieces_for) → hash-verified transfers
  from provider peers over the mesh's binary piece frames
  (node.request_piece) → assemble → `jax.device_put` via the engine's
  normal shard_params path → serve, with **zero local checkpoint**.

The DHT is kademlia-backed when that optional package exists; otherwise
records live in the in-process fallback (reference dht.py:25-38's same
degradation) — fine for co-located tests, real deployments run kademlia
or rely on the registry for manifest discovery.
"""

from __future__ import annotations

import asyncio
import logging

from ..utils import sha256_hex

logger = logging.getLogger("bee2bee_tpu.weights")

FETCH_CONCURRENCY = 8


async def publish_model_weights(
    node, dht, model_cfg, params, mesh_axes: dict[str, int] | None = None
):
    """Shard `params` into pieces, seed the node's piece store, announce
    manifest + providers on the DHT. Returns the ShardManifest.

    mesh_axes={} (or None) publishes whole-param pieces — what a
    single-chip peer fetches; a TP group publishes with its axis sizes so
    members fetch only their coordinates' slices."""
    from ..models import core
    from ..models.loader import _flatten
    from ..models.partition import flat_partition_specs
    from ..pieces import build_shard_manifest

    # the wire/manifest layout is canonical STACKED [L, ...]: a CPU
    # engine's unstacked list (core.unstack_layers) must be restacked —
    # np.asarray on a list of trees would serialize pointer garbage
    params = core.restack_layers(params)

    loop = asyncio.get_running_loop()

    def build():
        flat = _flatten(params)
        specs = (
            flat_partition_specs(params, mesh_axes, cfg=model_cfg)
            if mesh_axes
            else {k: () for k in flat}
        )
        return build_shard_manifest(model_cfg.name, flat, specs, mesh_axes or {})

    manifest, blobs = await loop.run_in_executor(None, build)
    for digest, blob in blobs.items():
        node.piece_store[digest] = blob
    node.manifests[model_cfg.name] = manifest

    await dht.announce_manifest(model_cfg.name, manifest.to_json(), node.addr)
    # announces are independent: batch them instead of one DHT RTT per piece
    sem = asyncio.Semaphore(FETCH_CONCURRENCY)

    async def announce(piece):
        async with sem:
            await dht.announce_piece(
                piece.sha256,
                node.addr,
                mesh_axis=piece.mesh_axis,
                shard_index=piece.shard_index,
            )

    await asyncio.gather(*(announce(p) for p in manifest.pieces))
    logger.info(
        "published %s: %d pieces, %.1f MiB",
        model_cfg.name, len(manifest.pieces), manifest.total_bytes / 2**20,
    )
    return manifest


async def _peer_for_addr(node, addr: str) -> str | None:
    """Resolve a DHT provider addr to a connected peer_id (dialing it if
    new). Per-(node, addr) lock: concurrent piece fetches must not open N
    parallel sockets to the same provider — the peer table only dedups
    after the hello round-trip."""
    locks = node.__dict__.setdefault("_weights_dial_locks", {})
    lock = locks.setdefault(addr, asyncio.Lock())
    async with lock:
        for pid, info in node.peers.items():
            if info.get("addr") == addr:
                return pid
        if await node.connect_bootstrap(addr):
            for _ in range(100):
                for pid, info in node.peers.items():
                    if info.get("addr") == addr:
                        return pid
                await node.clock.sleep(0.05)
    return None


async def fetch_model_from_mesh(
    node, dht, model: str, coords: dict[str, int] | None = None
):
    """Fetch manifest + pieces from mesh providers. With `coords`, only
    that mesh coordinate's pieces come back (a TP-group member's share);
    with coords=None, EVERY piece is fetched and sharded params are
    re-concatenated to full tensors (a host that owns all coordinates —
    it re-shards via the engine's own partition rules afterwards).
    Returns (model_cfg, flat {path: np.ndarray}) — hash-verified."""
    import numpy as np

    from ..models.config import get_config
    from ..pieces import ShardManifest, assemble_params_from_pieces

    rec = await dht.get_manifest(model)
    if rec is None:
        raise RuntimeError(f"no manifest on the DHT for model {model!r}")
    manifest = ShardManifest.from_json(rec["manifest"])
    needed = manifest.pieces if coords is None else manifest.pieces_for(coords)

    sem = asyncio.Semaphore(FETCH_CONCURRENCY)
    blobs: dict[str, bytes] = {}

    async def fetch(piece):
        if node.get_piece(piece.sha256) is not None:  # already local
            blobs[piece.sha256] = node.get_piece(piece.sha256)
            return
        providers = await dht.find_providers(piece.sha256, piece.shard_index)
        addrs = [p["addr"] for p in providers] or [rec.get("addr")]
        last_err: Exception | None = None
        async with sem:
            for addr in addrs:
                if not addr:
                    continue
                try:
                    pid = await _peer_for_addr(node, addr)
                    if pid is None:
                        continue
                    blobs[piece.sha256] = await node.request_piece(pid, piece.sha256)
                    return
                except Exception as e:  # noqa: BLE001 — try the next provider
                    last_err = e
        raise RuntimeError(
            f"no provider served piece {piece.sha256[:12]} for {piece.param}"
        ) from last_err

    results = await asyncio.gather(
        *(fetch(p) for p in needed), return_exceptions=True
    )
    errors = [r for r in results if isinstance(r, BaseException)]
    if errors:  # every sibling has finished — no orphaned transfers
        raise errors[0]
    if coords is not None:
        return get_config(model), assemble_params_from_pieces(manifest, blobs, coords)
    # full reassembly: verify + concat each param's shards (loader.load_native's
    # on-disk logic, over the wire)
    flat: dict[str, np.ndarray] = {}
    parts: dict[str, list] = {}
    concat_axis: dict[str, int] = {}
    for p in manifest.pieces:
        data = blobs[p.sha256]
        if sha256_hex(data) != p.sha256:
            raise ValueError(f"piece corrupt for {p.param}[{p.shard_index}]")
        arr = np.frombuffer(data, dtype=p.dtype).reshape(p.shape)
        if p.shard_count > 1:
            parts.setdefault(p.param, [None] * p.shard_count)[p.shard_index] = arr
            concat_axis[p.param] = p.axis
        else:
            flat[p.param] = arr
    for name, shards in parts.items():
        flat[name] = np.concatenate(shards, axis=concat_axis[name])
    return get_config(model), flat


async def serve_model_from_mesh(
    node, dht, model: str, mesh=None, engine_config=None, price_per_token: float = 0.0
):
    """The full join flow: fetch pieces → engine → TPUService → announce.
    The fresh peer serves with zero local checkpoint (VERDICT r2 task #5
    acceptance)."""
    from ..engine.engine import InferenceEngine
    from ..models.loader import _unflatten
    from ..services.tpu import TPUService

    import jax.numpy as jnp

    cfg, flat = await fetch_model_from_mesh(node, dht, model, coords=None)
    loop = asyncio.get_running_loop()

    def build_engine():
        import jax
        import numpy as np

        params = _unflatten(flat)
        dtype = jnp.dtype(engine_config.dtype) if engine_config else jnp.bfloat16

        def cast(path, a):
            # a quantized publisher ships {"q": int8, "s": f32} subtrees:
            # casting them to the engine dtype would silently undo the
            # quantization (int8 -> bf16 payload, truncated scales).
            # INTEGER payloads pass through; scale leaves keep f32. The
            # check must be issubdtype(..., np.integer) — ml_dtypes
            # bfloat16 is NOT an np.floating subtype, so a "not floating"
            # test would wrongly exempt every bf16 weight from the cast.
            if np.issubdtype(np.asarray(a).dtype, np.integer):
                return jnp.asarray(a)
            if path and str(getattr(path[-1], "key", "")) == "s":
                return jnp.asarray(a, jnp.float32)
            return jnp.asarray(a, dtype)

        params = jax.tree_util.tree_map_with_path(cast, params)
        return InferenceEngine(cfg, params, mesh=mesh, engine_config=engine_config)

    engine = await loop.run_in_executor(None, build_engine)
    svc = TPUService(cfg.name, price_per_token=price_per_token, engine=engine)
    await node.announce_service(svc)
    return svc
