"""run_p2p_node: the node orchestrator (reference p2p_runtime.py:843-954).

Boot order mirrors the reference's serve() stack (SURVEY §3.1): start the WS
node → start the HTTP gateway → connect bootstrap → load the service in an
executor (announce when ready) → sync with the registry → run forever.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging

from ..clock import get_clock
from ..config import NodeConfig, load_config, parse_mesh_shape
from ..utils import TaskTracker
from .node import P2PNode

logger = logging.getLogger("bee2bee_tpu.runtime")


def build_service(backend: str, model: str, cfg: NodeConfig, **kw):
    """Service factory for the CLI/runtime (reference run_p2p_node's backend
    switch, p2p_runtime.py:891-902)."""
    if backend == "tpu":
        from ..parallel import MeshSpec, build_mesh
        from ..services.tpu import TPUService

        shape = parse_mesh_shape(cfg.mesh_shape)
        mesh = build_mesh(MeshSpec.from_dict(shape)) if shape else None
        return TPUService(
            model,
            price_per_token=cfg.price_per_token,
            max_new_tokens=cfg.max_new_tokens,
            mesh=mesh,
            checkpoint_path=kw.get("checkpoint_path"),
            engine_config=cfg.engine_config(),
            lora_path=kw.get("lora_path"),
        )
    if backend == "ollama":
        from ..services.ollama import OllamaService

        return OllamaService(
            model,
            price_per_token=cfg.price_per_token,
            host=kw.get("ollama_host") or "http://127.0.0.1:11434",
            max_new_tokens=cfg.max_new_tokens,
        )
    if backend in ("hf_remote", "remote"):
        from ..services.remote import RemoteService

        return RemoteService(
            model, price_per_token=cfg.price_per_token, max_new_tokens=cfg.max_new_tokens
        )
    if backend == "fake":
        from ..services.fake import FakeService

        return FakeService(model, price_per_token=cfg.price_per_token)
    raise ValueError(f"unknown backend {backend!r} (tpu | ollama | hf_remote | fake)")


def parse_adapter_spec(spec: str) -> list[tuple[str, str]]:
    """Parse ``BEE2BEE_ADAPTERS`` / ``--adapters``: a comma-separated
    list of ``name=path.npz`` entries → [(name, path)]. Loud on junk —
    a silently-dropped adapter would serve the wrong tenant the base."""
    out: list[tuple[str, str]] = []
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, path = entry.partition("=")
        if not sep or not name.strip() or not path.strip():
            raise ValueError(
                f"bad adapter entry {entry!r}: expected name=path.npz"
            )
        out.append((name.strip(), path.strip()))
    return out


async def _preload_adapters(node, dht, svc, spec: str):
    """Load the configured adapters into the engine's pool, publish each
    as a pieces manifest on the DHT (peers page them in on demand), and
    announce residency. Failures are LOUD — the operator configured
    these adapters by name; serving without them is wrong output."""
    engine = getattr(svc, "engine", None)
    if engine is None or engine.adapter_pool is None:
        raise ValueError(
            "--adapters requires the tpu backend with max_adapters > 0"
        )
    from ..adapters.distrib import publish_adapter
    from ..train.lora import load_adapters

    loop = asyncio.get_running_loop()
    for name, path in parse_adapter_spec(spec):
        adapters, lcfg = await loop.run_in_executor(
            None, lambda p=path: load_adapters(p, model_cfg=engine.model_cfg)
        )
        await loop.run_in_executor(
            None, lambda n=name, a=adapters, c=lcfg: engine.load_adapter(n, a, c)
        )
        if dht is not None:
            await publish_adapter(
                node, dht, engine.model_cfg.name, name, adapters, lcfg
            )
        logger.info("adapter %s loaded from %s", name, path)
    await node.announce_adapters(svc)


def _parse_dht_bootstrap(spec: str) -> list[tuple[str, int]]:
    """"host:port,[v6::addr]:port,barehost" → [(host, port), ...].

    Bare hosts (including bare IPv6 literals, which contain colons) get
    the default kademlia port 8468; a malformed port raises rather than
    silently mis-resolving far from the misconfiguration."""
    out: list[tuple[str, int]] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if entry.startswith("["):  # [v6]:port or [v6]
            host, _, rest = entry[1:].partition("]")
            port_s = rest.lstrip(":")
        elif entry.count(":") == 1:
            host, _, port_s = entry.partition(":")
        else:  # zero colons = bare hostname; 2+ = bare IPv6 literal
            host, port_s = entry, ""
        if not port_s:
            out.append((host, 8468))
        elif port_s.isdigit():
            out.append((host, int(port_s)))
        else:
            raise ValueError(f"bad dht bootstrap entry {entry!r}: invalid port")
    return out


async def run_p2p_node(
    backend: str | None = "tpu",
    model: str = "distilgpt2",
    cfg: NodeConfig | None = None,
    bootstrap: str | None = None,
    serve_api: bool = True,
    registry_sync: bool = True,
    checkpoint_path: str | None = None,
    lora_path: str | None = None,  # LoRA adapters .npz (train/lora.py)
    ollama_host: str | None = None,
    ready_event: asyncio.Event | None = None,
    shutdown_event: asyncio.Event | None = None,
    stage_runner=None,  # host a preloaded pipeline stage (backend=None)
    dht=None,  # DHTNode for weight distribution (created on demand)
    publish_weights: bool = False,  # announce this node's params as pieces
    from_mesh: bool = False,  # tpu backend: fetch weights from the mesh DHT
    post_start=None,  # async callback(node) after services are set up —
    # the serve-pipeline coordinator wires its stage workers here
    tunnel: str | None = None,  # bore|ngrok|cloudflared|stub|auto: expose the
    # WS port through a public tunnel and announce ITS address (cloud-node
    # onboarding — tunnel.py; supersedes NAT auto-forward when set)
):
    """Boot a full serving node; runs until shutdown_event (or forever)."""
    cfg = cfg or load_config()
    node = P2PNode(
        host=cfg.host,
        port=cfg.port,
        announce_host=cfg.announce_host,
        announce_port=cfg.announce_port,
        api_port=cfg.api_port if serve_api else None,
    )
    await node.start()

    # everything after start() runs under the teardown guard: a failed
    # service build/load must not leak the listening node/gateway/monitor
    api_runner = None
    registry_tasks = None
    forwarder = None
    tun = None
    own_dht = dht is None  # stop a DHT we created ourselves
    try:
        if tunnel:
            from .. import tunnel as tunnel_mod

            tun = await tunnel_mod.open_tunnel_async(node.port, provider=tunnel)
            link = tunnel_mod.apply_to_node(node, tun)
            logger.info(
                "tunnel (%s) up: %s — join link: %s", tun.provider, tun.ws_url, link
            )

        # Announce-address resolution (reference p2p_runtime.py:195-274): when
        # no explicit announce host was configured, try NAT auto-forward →
        # STUN/echo public IP in an executor so router round-trips never block
        # the loop.
        if tun is None and not cfg.announce_host and cfg.auto_nat:
            from .. import nat

            loop = asyncio.get_running_loop()
            forwarder = nat.PortForwarder()
            with contextlib.suppress(Exception):
                mapping = await asyncio.wait_for(  # meshlint: ignore[ML-C001] -- real NAT/STUN round trip in an executor thread
                    loop.run_in_executor(None, forwarder.auto_forward, node.port), 15.0
                )
                if mapping.ok and mapping.public_ip:
                    node.announce_host = mapping.public_ip
                    # "stun" is observe-only: its external_port is the NAT
                    # mapping of a throwaway UDP socket, not our listener —
                    # only real mappings may override the announce port
                    if mapping.external_port and mapping.method != "stun":
                        node.announce_port = mapping.external_port
                    logger.info(
                        "NAT %s: announcing %s:%s", mapping.method,
                        node.announce_host, node.announce_port,
                    )

        if serve_api:
            from ..api import start_api_server

            api_runner = await start_api_server(node, cfg.host, cfg.api_port, api_key=cfg.api_key)

        if bootstrap or cfg.bootstrap_url:
            with contextlib.suppress(Exception):
                await node.connect_bootstrap(bootstrap or cfg.bootstrap_url)

        if stage_runner is not None:
            node.add_stage_runner(stage_runner)
            logger.info(
                "hosting stage %s/%s of %s (layers %s); join link: %s",
                stage_runner.spec.stage + 1, stage_runner.spec.n_stages,
                model, stage_runner.info["layers"], node.join_link(),
            )
        # adapter paging (adapters/) rides the same DHT leg as weight
        # distribution: a node with an adapter pool needs one to fetch
        # non-resident adapters on demand, and one to publish its own
        wants_adapters = backend == "tpu" and (
            cfg.adapters or cfg.max_adapters > 0
        )
        if (publish_weights or from_mesh or wants_adapters) and dht is None:
            from ..dht import DHTNode

            dht = DHTNode(port=cfg.dht_port)
            await dht.start(_parse_dht_bootstrap(cfg.dht_bootstrap) or None)
        if dht is not None:
            node.dht = dht  # ensure_adapter's fetch path reads this

        if backend == "tpu" and node.disagg_role == "draft":
            # the draft disagg role hosts ONLY the drafter program
            # (meshnet/draft.py): no target engine, no gen_request
            # service — serving peers stream draft_request frames here.
            # Loaded in an executor (weights init/load is sync compute);
            # a bad drafter spec fails the boot typed (DrafterLoadError).
            drafter_model = (
                cfg.drafter if cfg.drafter and cfg.drafter != "mesh"
                else model
            )
            k = cfg.spec_tokens or 6
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                None,
                lambda: node.enable_draft_server(
                    "auto" if checkpoint_path else drafter_model,
                    spec_tokens=k, dtype=cfg.dtype,
                    checkpoint_path=checkpoint_path,
                ),
            )
            backend = None  # skip the target-service build below
            logger.info(
                "hosting draft role (%s, K=%s); join link: %s",
                drafter_model, k, node.join_link(),
            )

        if backend == "tpu" and from_mesh:
            if lora_path:
                # silently serving the base while the operator believes the
                # adapters are applied would be wrong outputs with no signal
                raise ValueError(
                    "--lora is not supported with --from-mesh (mesh-fetched "
                    "weights + local adapters): serve from a local "
                    "--checkpoint, or publish the merged weights"
                )
            # the zero-local-checkpoint join: manifest + pieces come from
            # mesh providers via the DHT (meshnet/weights.py)
            from .weights import serve_model_from_mesh

            shape = parse_mesh_shape(cfg.mesh_shape)
            join_mesh = None
            if shape:
                from ..parallel import MeshSpec, build_mesh

                join_mesh = build_mesh(MeshSpec.from_dict(shape))
            svc = await serve_model_from_mesh(
                node, dht, model,
                mesh=join_mesh,
                engine_config=cfg.engine_config(),
                price_per_token=cfg.price_per_token,
            )
            logger.info("serving %s from mesh pieces; join link: %s", model, node.join_link())
        elif backend is not None:
            svc = build_service(
                backend, model, cfg,
                checkpoint_path=checkpoint_path, lora_path=lora_path,
                ollama_host=ollama_host,
            )
            loop = asyncio.get_running_loop()
            if hasattr(svc, "load_sync"):
                await loop.run_in_executor(None, svc.load_sync)
            await node.announce_service(svc)
            logger.info("serving %s via %s; join link: %s", model, backend, node.join_link())
        elif stage_runner is None and node.draft_server is None:
            logger.info(
                "stage worker awaiting part_load for %s; join link: %s",
                model, node.join_link(),
            )

        if backend == "tpu" and cfg.adapters:
            # preload + publish the configured adapters (BEE2BEE_ADAPTERS
            # / serve-tpu --adapters): this node serves them immediately
            # and seeds the mesh so peers can page them in
            await _preload_adapters(node, dht, svc, cfg.adapters)

        if publish_weights and backend == "tpu":
            # publishes after a --from-mesh join too: a joined peer reseeds
            # the swarm as a new piece provider
            from .weights import publish_model_weights

            engine = getattr(svc, "engine", None)
            if engine is not None:
                await publish_model_weights(
                    node, dht, engine.model_cfg, engine.params,
                    parse_mesh_shape(cfg.mesh_shape),
                )

        if registry_sync:
            from ..registry import RegistryClient

            client = RegistryClient()
            if client.enabled:
                registry_tasks = TaskTracker("runtime")
                registry_tasks.spawn(client.sync_loop(node))

        if post_start is not None:
            await post_start(node)
        if ready_event is not None:
            ready_event.set()
        if shutdown_event is not None:
            await shutdown_event.wait()
        else:
            while True:
                await get_clock().sleep(3600)
    finally:
        if tun is not None:
            with contextlib.suppress(Exception):
                tun.close()
        if own_dht and dht is not None:
            with contextlib.suppress(Exception):
                await dht.stop()
        if registry_tasks is not None:
            await registry_tasks.cancel_all()
        if api_runner is not None:
            await api_runner.cleanup()
        if forwarder is not None and forwarder.mappings:
            loop = asyncio.get_running_loop()
            with contextlib.suppress(Exception):
                await asyncio.wait_for(  # meshlint: ignore[ML-C001] -- real NAT teardown in an executor thread
                    loop.run_in_executor(None, forwarder.cleanup), 10.0
                )
        await node.stop()
    return node
