"""Mesh health plane: telemetry digests, SLO burn-rate tracking, and an
incident flight recorder.

PR 5 gave every node rich *local* instruments (metrics.py histograms,
tracing.py spans). This module turns them into the *operational* layer the
ROADMAP's front-door items consume:

- ``build_digest()`` folds the local metrics registry into a compact,
  wire-portable summary (histogram count/sum/percentiles, pool occupancy,
  batch fill, spec acceptance, per-stage task counters). Nodes gossip it
  on the ping cadence as a ``TELEMETRY`` frame (meshnet/node.py) and store
  peers' digests in a ``HealthStore`` with staleness stamps, so *every*
  node can serve the merged fleet view at ``GET /mesh/health``.
- ``SloTracker`` evaluates a declarative SLO config (``ttft_p95 < 2s``
  style latency objectives and error-rate objectives) against the local
  histograms with **multi-window burn rates** (fast + slow window, Google
  SRE style): burn rate = (bad fraction over the window) / error budget.
  Exposed as ``bee2bee_slo_*`` gauges and ``GET /slo`` — the signal the
  future SLO-aware router and admission controller route on.
- ``FlightRecorder`` keeps a bounded ring of recent span completions,
  frame-op events and metric deltas; typed failures (StageDead /
  StageTimeout, paged-pool exhaustion, gen_error, SLO burn trips) snapshot
  the ring plus the stitched trace of the offending request into an
  on-disk **incident bundle**, listable via ``GET /debug/incidents``.

Everything here honors the telemetry never-throw contract (metrics.py,
tracing.py): recording, gossiping and snapshotting must not take down the
serving path. Disk writes are best-effort; a full disk costs incident
bundles, never generations.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

from .clock import Clock, get_clock, resolve_clock
from .metrics import Counter, Gauge, Histogram, MetricsRegistry, get_registry
from .tracing import current_trace_ctx, get_tracer, stitch_trace
from .utils import bee2bee_home, load_json_source, new_id

logger = logging.getLogger("bee2bee_tpu.health")

DIGEST_VERSION = 1

# the metric allowlist a digest summarizes. A digest is a WIRE payload
# repeated every ping interval to every peer: it must stay compact and
# schema-stable, so the contents are enumerated here instead of shipping
# the whole registry snapshot (which grows with every instrumented
# subsystem and with label cardinality).
DIGEST_HISTOGRAMS = (
    "engine.queue_wait_ms",
    "engine.ttft_ms",
    "engine.inter_token_ms",
    "engine.e2e_latency_ms",
    "service.execute_ms",
    # worker-side stage compute (engine/stage_runner.py, measured inside
    # the concurrency gate): its p50 feeds the coordinator's microbatch
    # auto-depth heuristic (resolve_microbatches)
    "pipeline.stage_task_ms",
)
DIGEST_GAUGES = (
    "engine.batch_fill",
    "engine.active_rows",
    "engine.paged_blocks_in_use",
    "engine.paged_blocks_free",
    "engine.paged_blocks_total",
)
DIGEST_COUNTERS = (
    "engine.tokens_generated",
    "engine.spec_drafted",
    "engine.spec_accepted",
    "gen.requests",
    "gen.errors",
    "mesh.relay_hops",
    "pipeline.recoveries",
    "pipeline.session_failovers",
)
# labeled counter whose per-label breakdown rides the digest (the MPMD
# bubble-fraction analysis needs per-stage task counts, not one total)
DIGEST_STAGE_TASKS = "pipeline.stage_tasks"

# ------------------------------------------------- pipeline bubble fraction
#
# ISSUE 10: the MPMD serving analogue of arxiv 2412.14374's bubble
# analysis. A stage worker's stage.task spans (meshnet/pipeline.py) record
# exactly when its compute was busy; everything else inside the
# observation window is bubble — the stage sat idle while its neighbors
# computed. Derived, never sampled: the gauges below are recomputed from
# the local tracer ring at digest-build/scrape time, and the same interval
# math serves stitched cross-node traces (bench + /trace consumers).

BUBBLE_WINDOW_S = 30.0

# stage.task spans that count as BUSY serving compute. part_load
# (checkpoint read + XLA compile) and part_release also run inside
# stage.task spans; counting a failover reload as "busy" would report
# ~zero bubble during exactly the incident when the pipeline is
# maximally stalled. Literal protocol task-kind values (health cannot
# import meshnet.pipeline — it imports health for the recorder).
_BUBBLE_TASK_KINDS = ("part_forward", "part_forward_relay", "decode_run")

_G_BUBBLE = get_registry().gauge(
    "pipeline.bubble_fraction",
    "fraction of the observation window this node's pipeline stages sat "
    "idle (1 - busy; from stage.task spans)",
)
_G_STAGE_BUSY = get_registry().gauge(
    "pipeline.stage_busy_fraction",
    "per-stage busy fraction over the observation window",
)


def _merge_busy_ms(intervals: list[tuple[float, float]]) -> float:
    """Total covered milliseconds of possibly-overlapping [a, b) spans —
    concurrent forwards on one stage must not double-count busy time."""
    busy = 0.0
    cur_a = cur_b = None
    for a, b in sorted(intervals):
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                busy += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        busy += cur_b - cur_a
    return busy


def bubble_from_spans(
    spans: list[dict],
    window_start_ms: float | None = None,
    window_end_ms: float | None = None,
) -> dict | None:
    """Bubble fraction from ``stage.task`` span dicts (local tracer
    output OR a stitched cross-node timeline — spans may carry a ``node``
    key). Spans are clipped to the window (default: the spans' own
    extent); per-stage busy intervals merge before summing, so concurrent
    chains never count a stage >100% busy. Returns None when no completed
    stage.task span lands in the window.

    ``bubble_fraction`` is 1 - mean per-stage busy fraction: 0.0 means
    every stage computed wall-to-wall, 0.5 means the average stage sat
    idle half the window — the number the interleaved scheduler exists
    to drive toward zero."""
    stage_spans = []
    for s in spans or []:
        if s.get("name") != "stage.task":
            continue
        kind = (s.get("attrs") or {}).get("kind")
        if kind is not None and kind not in _BUBBLE_TASK_KINDS:
            continue  # loads/releases are stall time, not serving compute
        d = s.get("duration_ms")
        a = s.get("start_ms")
        if d is None or a is None or d < 0:
            continue  # open/malformed spans carry no busy interval
        stage_spans.append(s)
    if not stage_spans:
        return None
    if window_start_ms is None:
        window_start_ms = min(s["start_ms"] for s in stage_spans)
    if window_end_ms is None:
        window_end_ms = max(s["start_ms"] + s["duration_ms"]
                            for s in stage_spans)
    window_ms = window_end_ms - window_start_ms
    if window_ms <= 0:
        return None
    per: dict[str, list[tuple[float, float]]] = {}
    tasks: dict[str, int] = {}
    for s in stage_spans:
        a = max(s["start_ms"], window_start_ms)
        b = min(s["start_ms"] + s["duration_ms"], window_end_ms)
        if b <= a:
            continue
        stage = (s.get("attrs") or {}).get("stage")
        node = s.get("node")
        key = (f"{node}/" if node else "") + (
            str(stage) if stage is not None else "?"
        )
        per.setdefault(key, []).append((a, b))
        tasks[key] = tasks.get(key, 0) + 1
    if not per:
        return None
    stages = {
        key: {
            "busy_fraction": round(
                min(_merge_busy_ms(iv) / window_ms, 1.0), 4
            ),
            "tasks": tasks[key],
        }
        for key, iv in per.items()
    }
    mean_busy = sum(v["busy_fraction"] for v in stages.values()) / len(stages)
    return {
        "window_s": round(window_ms / 1000.0, 3),
        "bubble_fraction": round(max(0.0, 1.0 - mean_busy), 4),
        "stages": stages,
    }


def local_stage_idleness(
    window_s: float = BUBBLE_WINDOW_S, tracer=None
) -> dict | None:
    """This node's bubble fraction over the trailing ``window_s``,
    refreshed into the ``pipeline.bubble_fraction`` /
    ``pipeline.stage_busy_fraction{stage=}`` gauges. With no stage.task
    span in the window the gauges CLEAR (the empty-gauge contract: a
    stage that stopped serving drops out instead of freezing its last
    reading) and None is returned."""
    try:
        tr = tracer or get_tracer()
        now_ms = get_clock().time() * 1000.0
        info = bubble_from_spans(
            tr.recent(limit=2048, name="stage.task"),
            now_ms - window_s * 1000.0, now_ms,
        )
        if info is None:
            _G_BUBBLE.clear()
            for labels, _v in _G_STAGE_BUSY.series():
                _G_STAGE_BUSY.clear(**dict(labels))
            return None
        _G_BUBBLE.set(info["bubble_fraction"])
        fresh = set()
        for key, entry in info["stages"].items():
            _G_STAGE_BUSY.set(entry["busy_fraction"], stage=key)
            fresh.add((("stage", key),))
        for labels, _v in _G_STAGE_BUSY.series():
            if tuple(labels) not in fresh:
                _G_STAGE_BUSY.clear(**dict(labels))
        return info
    except Exception:  # noqa: BLE001 — telemetry never breaks the caller
        return None


# live-digest providers: subsystems that derive their digest entry at
# build time (refreshing their gauges as a side effect) register here —
# e.g. the engine economics plane (engine/introspect.py) contributes the
# `introspect` block. A dict, not a list: re-registration replaces, so
# module reloads/tests can't stack duplicates.
_DIGEST_PROVIDERS: dict[str, Callable[[], dict | None]] = {}


def register_digest_provider(key: str, fn: Callable[[], dict | None]) -> None:
    """Register a live-digest provider: ``fn()`` returns the payload for
    digest[key] (None = omit — the absent-subsystem contract)."""
    _DIGEST_PROVIDERS[key] = fn


def run_digest_providers() -> dict[str, dict]:
    """Every provider's current payload (never-throw per provider). Also
    the scrape-time gauge-refresh hook: api.py calls this at /metrics so
    provider-owned gauges (MFU, HBM ledger, pool forecast) are current."""
    out: dict[str, dict] = {}
    for key, fn in list(_DIGEST_PROVIDERS.items()):
        try:
            payload = fn()
        except Exception:  # noqa: BLE001 — telemetry never throws
            logger.exception("digest provider %r failed", key)
            continue
        if payload is not None:
            out[key] = payload
    return out


def build_digest(registry: MetricsRegistry | None = None) -> dict:
    """Fold the metrics registry into a compact wire-portable summary.

    Missing metrics (e.g. a client-only node that never imported the
    engine) are simply absent from the digest — receivers treat absent
    keys as "this node doesn't run that subsystem", not as zero.

    On the live path (no explicit registry) the digest also carries
    ``pipeline_bubble`` — this node's stage-idleness breakdown derived
    from its tracer's stage.task spans — so ``/mesh/health`` shows
    fleet-wide pipeline bubbles without another scrape. Unit digests
    built from throwaway registries stay pure registry summaries."""
    live = registry is None
    reg = registry or get_registry()
    digest: dict[str, Any] = {"v": DIGEST_VERSION, "ts": get_clock().time()}
    if live:
        bubble = local_stage_idleness()
        if bubble is not None:
            digest["pipeline_bubble"] = bubble
        # provider-derived entries (engine economics plane etc.): each
        # refreshes its own gauges and returns its digest block
        digest.update(run_digest_providers())
    hists: dict[str, dict] = {}
    for name in DIGEST_HISTOGRAMS:
        m = reg.get(name)
        if not isinstance(m, Histogram):
            continue
        count, total = m.totals()
        if count == 0:
            continue
        hists[name] = {
            "count": count,
            "sum": round(total, 3),
            "p50": m.percentile(0.5),
            "p95": m.percentile(0.95),
            "p99": m.percentile(0.99),
        }
    if hists:
        digest["hist"] = hists
    gauges: dict[str, float] = {}
    for name in DIGEST_GAUGES:
        m = reg.get(name)
        if isinstance(m, Gauge) and m.series():
            gauges[name] = m.value()
    if gauges:
        digest["gauge"] = gauges
    counters: dict[str, float] = {}
    for name in DIGEST_COUNTERS:
        m = reg.get(name)
        if isinstance(m, Counter):
            counters[name] = m.total()
    if counters:
        digest["counter"] = counters
    stage = reg.get(DIGEST_STAGE_TASKS)
    if isinstance(stage, Counter):
        by_kind = {
            ",".join(v for _, v in labels) or "_": value
            for labels, value in stage.series()
        }
        if by_kind:
            digest["stage_tasks"] = by_kind
    drafted = counters.get("engine.spec_drafted") or 0.0
    if drafted:
        digest["spec_acceptance"] = round(
            (counters.get("engine.spec_accepted") or 0.0) / drafted, 4
        )
    return digest


# --------------------------------------------------------------- health store


class HealthStore:
    """Per-peer telemetry digests with staleness stamps.

    A digest older than ``ttl_s`` is STALE: it stays readable (``all()``)
    for debugging but is excluded from ``fresh()`` — and therefore from
    ``/mesh/health`` aggregates and the peer-labeled exposition, matching
    the registry's empty-gauge contract (a reading that stopped arriving
    must drop out, not serve forever as if current)."""

    def __init__(self, ttl_s: float = 45.0, clock: Clock | None = None):
        self.ttl_s = ttl_s
        self._clock = resolve_clock(clock)
        self._lock = threading.Lock()
        self._digests: dict[str, dict] = {}  # peer_id -> digest
        self._received: dict[str, float] = {}  # peer_id -> local arrival time

    def update(self, peer_id: str, digest: dict) -> None:
        if not peer_id or not isinstance(digest, dict):
            return
        with self._lock:
            self._digests[peer_id] = digest
            self._received[peer_id] = self._clock.time()

    def drop(self, peer_id: str) -> None:
        with self._lock:
            self._digests.pop(peer_id, None)
            self._received.pop(peer_id, None)

    def age_s(self, peer_id: str) -> float | None:
        with self._lock:
            t = self._received.get(peer_id)
        return None if t is None else self._clock.time() - t

    def fresh(self) -> dict[str, dict]:
        """{peer_id: digest} for peers heard from within the TTL."""
        now = self._clock.time()
        with self._lock:
            return {
                pid: d
                for pid, d in self._digests.items()
                if now - self._received[pid] <= self.ttl_s
            }

    def all(self) -> dict[str, dict]:
        """Every stored digest annotated with age/staleness (debug view)."""
        now = self._clock.time()
        with self._lock:
            return {
                pid: {
                    **d,
                    "age_s": round(now - self._received[pid], 3),
                    "stale": now - self._received[pid] > self.ttl_s,
                }
                for pid, d in self._digests.items()
            }

    def stale_peers(self) -> list[str]:
        now = self._clock.time()
        with self._lock:
            return sorted(
                pid
                for pid in self._digests
                if now - self._received[pid] > self.ttl_s
            )


def digest_slo_burn(digest: dict | None) -> tuple[float, bool]:
    """(max fast-window burn rate, is_burning) from a digest's SLO brief.
    ``is_burning`` uses the same rule the router's exclusion does: any
    objective reporting burning/tripped status."""
    if not isinstance(digest, dict):
        return 0.0, False
    brief = digest.get("slo")
    if not isinstance(brief, dict):
        return 0.0, False
    burn = 0.0
    burning = False
    for e in brief.values():
        if not isinstance(e, dict):
            continue
        try:
            burn = max(burn, float(e.get("burn_fast") or 0.0))
        except (TypeError, ValueError):
            pass
        if e.get("status") in ("burning", "tripped"):
            burning = True
    return burn, burning


def controller_aggregates(
    digests: dict[str, dict], serving: set | None = None
) -> dict:
    """Controller-grade fleet aggregates (fleet/controller.py's input,
    also served under ``/mesh/health``'s ``aggregate.fleet``).

    Callers pass FRESH digests only (``HealthStore.fresh()`` + the local
    live digest) — a stale digest must drop out of these numbers before
    it can trigger a scale action, and freshness is the store's job, not
    re-derived here.

    Bucketing rules, which ARE the capacity semantics:

    - ``draining`` peers are leaving: excluded from the eligible count
      and from every headroom signal (their emptying batch would read as
      fake headroom exactly while the fleet is losing a replica);
    - ``standby`` / ``warming`` peers receive no routed traffic yet, so
      their (idle) signals say nothing about serving capacity — counted
      in their own buckets only;
    - with ``serving`` given, a peer must be in it to count as eligible
      (a client-only node gossips a digest too, but it is not a
      replica).

    Headroom/burn signals over the ELIGIBLE set only: ``burning`` /
    ``burn_fast_max`` from the SLO briefs, ``fill_mean`` (absent
    batch-fill gauges count as 0 — no engine, no pressure),
    ``queue_p95_max``, ``pool_free_min``, ``active_rows_total``.

    ``pool_eta_s`` (ISSUE 20) is the pool-occupancy trend FORECAST: the
    soonest projected paged-pool exhaustion across eligible peers, read
    from their gossiped trend digests (obs/). A trend slope is relative
    — fraction of the level per minute, normalized by
    ``max(mean, scale_floor)`` (tsring.SeriesSpec; pool_free_frac's
    floor is 0.05, kept in lockstep by tests/test_obs.py) — so with the
    current level ``m`` and relative slope ``s < 0`` the absolute drain
    rate is ``s * max(m, 0.05)`` per minute and exhaustion lands in
    ``m / (-s * max(m, 0.05))`` minutes. None when no eligible peer
    reports a falling pool trend."""
    eligible: dict[str, dict] = {}
    draining: list[str] = []
    standby: list[str] = []
    warming: list[str] = []
    other: list[str] = []
    for pid, d in digests.items():
        if not isinstance(d, dict):
            continue
        if d.get("draining"):
            draining.append(pid)
            continue
        state = d.get("fleet_state")
        if state == "standby":
            standby.append(pid)
            continue
        if state == "warming":
            warming.append(pid)
            continue
        if serving is not None and pid not in serving:
            other.append(pid)
            continue
        eligible[pid] = d
    burning_ids: list[str] = []
    burn_max = 0.0
    fills: list[float] = []
    q95s: list[float] = []
    pool_fracs: list[float] = []
    pool_etas: list[tuple[float, str]] = []
    rows = 0.0
    for pid, d in eligible.items():
        burn, is_burning = digest_slo_burn(d)
        burn_max = max(burn_max, burn)
        if is_burning:
            burning_ids.append(pid)
        gauge = d.get("gauge") or {}
        fills.append(
            min(max(float(gauge.get("engine.batch_fill") or 0.0), 0.0), 1.0)
        )
        qw = (d.get("hist") or {}).get("engine.queue_wait_ms") or {}
        q95s.append(float(qw.get("p95") or 0.0))
        total = float(gauge.get("engine.paged_blocks_total") or 0.0)
        if total > 0:
            free = float(gauge.get("engine.paged_blocks_free") or 0.0)
            pool_fracs.append(min(max(free / total, 0.0), 1.0))
        rows += float(gauge.get("engine.active_rows") or 0.0)
        pf = ((d.get("trend") or {}).get("series") or {}).get(
            "pool_free_frac"
        ) or {}
        try:
            mean = float(pf["mean"])
            slope = float(pf["slope"])
        except (KeyError, TypeError, ValueError):
            mean = slope = 0.0
        if slope < -1e-4 and mean > 0:
            drain_per_min = -slope * max(mean, 0.05)  # tsring scale_floor
            pool_etas.append((round(60.0 * mean / drain_per_min, 1), pid))
    n = len(eligible)
    pool_eta = min(pool_etas) if pool_etas else None
    return {
        "nodes": len(digests),
        "eligible": n,
        "eligible_ids": sorted(eligible),
        "draining": sorted(draining),
        "standby": sorted(standby),
        "warming": sorted(warming),
        "other": sorted(other),
        "burning": len(burning_ids),
        "burning_ids": sorted(burning_ids),
        "burning_frac": round(len(burning_ids) / n, 4) if n else 0.0,
        "burn_fast_max": round(burn_max, 4),
        "fill_mean": round(sum(fills) / n, 4) if n else 0.0,
        "queue_p95_max": round(max(q95s), 3) if q95s else 0.0,
        "pool_free_min": round(min(pool_fracs), 4) if pool_fracs else None,
        "pool_eta_s": pool_eta[0] if pool_eta else None,
        "pool_eta_peer": pool_eta[1] if pool_eta else None,
        "active_rows_total": rows,
    }


def fleet_view(local_peer_id: str, local_digest: dict, store: HealthStore,
               serving: set | None = None) -> dict:
    """The merged ``/mesh/health`` payload: the local node's digest plus
    every FRESH peer digest, with fleet-level aggregates. Stale peers are
    listed by id but contribute nothing to the aggregates. ``serving``
    (the controller's replica universe — api.py passes
    ``node.fleet.serving_peers()``) scopes the ``fleet`` aggregate block
    to actual replicas, so the endpoint shows the exact numbers a scale
    decision reads; without it every gossiping node counts as eligible."""
    peers: dict[str, dict] = {local_peer_id: {**local_digest, "age_s": 0.0}}
    for pid, digest in store.fresh().items():
        age = store.age_s(pid)
        peers[pid] = {**digest, "age_s": round(age, 3) if age is not None else None}
    agg: dict[str, float] = {"nodes": len(peers)}
    p95s, queue_p95s, tokens, blocks, rows = [], [], 0.0, 0.0, 0.0
    bubbles = []
    goodputs, mfus, headrooms, storming = [], [], [], []
    for pid, d in peers.items():
        hist = d.get("hist") or {}
        ttft = hist.get("engine.ttft_ms")
        if ttft:
            p95s.append(float(ttft.get("p95") or 0.0))
        qw = hist.get("engine.queue_wait_ms")
        if qw:
            queue_p95s.append(float(qw.get("p95") or 0.0))
        counter = d.get("counter") or {}
        tokens += float(counter.get("engine.tokens_generated") or 0.0)
        gauge = d.get("gauge") or {}
        blocks += float(gauge.get("engine.paged_blocks_in_use") or 0.0)
        rows += float(gauge.get("engine.active_rows") or 0.0)
        bubble = (d.get("pipeline_bubble") or {}).get("bubble_fraction")
        if bubble is not None:
            bubbles.append(float(bubble))
        # engine economics (digest `introspect` block): fleet goodput is
        # the SUM across engine peers; MFU averages over reporters; HBM
        # headroom keeps the worst peer — the one a router/controller
        # must notice — and retrace-storming peers are listed by id
        intro = d.get("introspect") or {}
        if intro.get("goodput_tokens_per_s") is not None:
            goodputs.append(float(intro["goodput_tokens_per_s"]))
        if intro.get("mfu") is not None:
            mfus.append(float(intro["mfu"]))
        hr = (intro.get("hbm") or {}).get("headroom_frac")
        if hr is not None:
            headrooms.append((float(hr), pid))
        if intro.get("storming"):
            storming.append(pid)
    if p95s:
        agg["ttft_p95_ms_max"] = max(p95s)
    if queue_p95s:
        agg["queue_wait_p95_ms_max"] = max(queue_p95s)
    if bubbles:
        # fleet-wide stage idleness: the mean of the stage-hosting peers'
        # bubble fractions (nodes with no stage traffic report nothing)
        agg["bubble_fraction_mean"] = round(sum(bubbles) / len(bubbles), 4)
    if goodputs:
        agg["goodput_tokens_per_s_total"] = round(sum(goodputs), 3)
    if mfus:
        agg["mfu_mean"] = round(sum(mfus) / len(mfus), 6)
    if headrooms:
        worst = min(headrooms)
        agg["hbm_headroom_frac_min"] = worst[0]
        agg["hbm_headroom_min_peer"] = worst[1]
    if storming:
        agg["retrace_storming_peers"] = sorted(storming)
    agg["tokens_generated_total"] = tokens
    agg["paged_blocks_in_use_total"] = blocks
    agg["active_rows_total"] = rows
    # the controller-grade breakdown (fleet/controller.py consumes the
    # same function over the same fresh digests): /mesh/health shows the
    # exact numbers a scale decision would read
    agg["fleet"] = controller_aggregates(peers, serving=serving)
    return {
        "node": local_peer_id,
        "ttl_s": store.ttl_s,
        "peers": peers,
        "stale_peers": store.stale_peers(),
        "aggregate": agg,
    }


def render_fleet_prom(view: dict) -> str:
    """Prometheus text exposition of a fleet view, one series per FRESH
    peer under a ``peer`` label. Built from a throwaway registry each
    scrape, so a peer absent from the view simply has no series — the
    drop-out contract for stale peers comes for free."""
    reg = MetricsRegistry()
    up = reg.gauge("mesh.peer_up", "1 for every fresh peer digest in the view")
    age = reg.gauge("mesh.peer_digest_age_s", "digest age at scrape")
    ttft = reg.gauge("mesh.peer_ttft_p95_ms", "peer-reported TTFT p95")
    qwait = reg.gauge("mesh.peer_queue_wait_p95_ms", "peer-reported queue-wait p95")
    e2e = reg.gauge("mesh.peer_e2e_p95_ms", "peer-reported e2e latency p95")
    fill = reg.gauge("mesh.peer_batch_fill", "peer-reported batch fill")
    rows = reg.gauge("mesh.peer_active_rows", "peer-reported active rows")
    used = reg.gauge("mesh.peer_paged_blocks_in_use", "peer-reported pool blocks used")
    free = reg.gauge("mesh.peer_paged_blocks_free", "peer-reported pool blocks free")
    toks = reg.gauge("mesh.peer_tokens_generated", "peer-reported tokens generated")
    errs = reg.gauge("mesh.peer_gen_errors", "peer-reported failed generations")
    acc = reg.gauge("mesh.peer_spec_acceptance", "peer-reported spec acceptance")
    bub = reg.gauge(
        "mesh.peer_bubble_fraction", "peer-reported pipeline bubble fraction"
    )
    # engine economics (ISSUE 15): the digest `introspect` block's
    # fleet-visible numbers under the same peer-labeled drop-out contract
    mfu = reg.gauge("mesh.peer_mfu", "peer-reported engine MFU")
    gput = reg.gauge(
        "mesh.peer_goodput_tokens_per_s", "peer-reported useful tokens/s"
    )
    hbm = reg.gauge(
        "mesh.peer_hbm_headroom_frac", "peer-reported device memory headroom"
    )
    storm = reg.gauge(
        "mesh.peer_retrace_storming",
        "1 while the peer reports a recent retrace storm",
    )
    for pid, d in (view.get("peers") or {}).items():
        up.set(1, peer=pid)
        if d.get("age_s") is not None:
            age.set(d["age_s"], peer=pid)
        hist = d.get("hist") or {}
        if "engine.ttft_ms" in hist:
            ttft.set(hist["engine.ttft_ms"].get("p95") or 0.0, peer=pid)
        if "engine.queue_wait_ms" in hist:
            qwait.set(hist["engine.queue_wait_ms"].get("p95") or 0.0, peer=pid)
        if "engine.e2e_latency_ms" in hist:
            e2e.set(hist["engine.e2e_latency_ms"].get("p95") or 0.0, peer=pid)
        gauge = d.get("gauge") or {}
        if "engine.batch_fill" in gauge:
            fill.set(gauge["engine.batch_fill"], peer=pid)
        if "engine.active_rows" in gauge:
            rows.set(gauge["engine.active_rows"], peer=pid)
        if "engine.paged_blocks_in_use" in gauge:
            used.set(gauge["engine.paged_blocks_in_use"], peer=pid)
        if "engine.paged_blocks_free" in gauge:
            free.set(gauge["engine.paged_blocks_free"], peer=pid)
        counter = d.get("counter") or {}
        if "engine.tokens_generated" in counter:
            toks.set(counter["engine.tokens_generated"], peer=pid)
        if "gen.errors" in counter:
            errs.set(counter["gen.errors"], peer=pid)
        if d.get("spec_acceptance") is not None:
            acc.set(d["spec_acceptance"], peer=pid)
        bubble = d.get("pipeline_bubble") or {}
        if bubble.get("bubble_fraction") is not None:
            bub.set(bubble["bubble_fraction"], peer=pid)
        intro = d.get("introspect") or {}
        if intro.get("mfu") is not None:
            mfu.set(intro["mfu"], peer=pid)
        if intro.get("goodput_tokens_per_s") is not None:
            gput.set(intro["goodput_tokens_per_s"], peer=pid)
        headroom = (intro.get("hbm") or {}).get("headroom_frac")
        if headroom is not None:
            hbm.set(headroom, peer=pid)
        if intro.get("storming"):
            storm.set(1, peer=pid)
    return reg.render()


# ------------------------------------------------------------- SLO tracking


@dataclass(frozen=True)
class SloObjective:
    """One declarative objective.

    kind="latency": good events are observations of histogram ``metric``
    at or under ``threshold_ms`` (the threshold should sit on a bucket
    bound — the default buckets are powers of two ms — since bucketed
    counts can only split at bounds; an off-bound threshold is rounded
    DOWN to the nearest bound, the conservative direction).

    kind="error_rate": good events are ``total_metric`` counts minus
    ``errors_metric`` counts (both counters).

    ``target`` is the availability goal, e.g. 0.95 ⇒ a 5% error budget.
    """

    name: str
    kind: str  # "latency" | "error_rate"
    target: float
    metric: str = ""  # latency: histogram name
    threshold_ms: float = 0.0  # latency only
    errors_metric: str = ""  # error_rate: counters
    total_metric: str = ""

    @property
    def budget(self) -> float:
        return max(1.0 - self.target, 1e-9)

    def describe(self) -> dict:
        out = {"name": self.name, "kind": self.kind, "target": self.target}
        if self.kind == "latency":
            out["metric"] = self.metric
            out["threshold_ms"] = self.threshold_ms
        else:
            out["errors_metric"] = self.errors_metric
            out["total_metric"] = self.total_metric
        return out


DEFAULT_SLO_CONFIG: tuple[dict, ...] = (
    {"name": "ttft_p95", "kind": "latency", "metric": "engine.ttft_ms",
     "threshold_ms": 2048.0, "target": 0.95},
    {"name": "queue_wait_p99", "kind": "latency",
     "metric": "engine.queue_wait_ms", "threshold_ms": 4096.0, "target": 0.99},
    {"name": "gen_error_rate", "kind": "error_rate",
     "errors_metric": "gen.errors", "total_metric": "gen.requests",
     "target": 0.99},
)


def parse_slo_config(entries) -> list[SloObjective]:
    """Validate a list of objective dicts; raises ValueError on junk (a
    mis-typed SLO config must fail loudly at boot, not route on garbage)."""
    out: list[SloObjective] = []
    seen_names: set[str] = set()
    for e in entries:
        if not isinstance(e, dict) or not e.get("name"):
            raise ValueError(f"SLO entry needs a name: {e!r}")
        name = str(e["name"])
        # SloTracker keys its snapshot deques by name: two objectives
        # sharing one would interleave unrelated cumulative counts and
        # burn-rate on garbage
        if name in seen_names:
            raise ValueError(f"duplicate SLO objective name {name!r}")
        seen_names.add(name)
        kind = e.get("kind")
        target = float(e.get("target", 0.0))
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO {e['name']!r}: target must be in (0, 1)")
        if kind == "latency":
            if not e.get("metric") or float(e.get("threshold_ms", 0)) <= 0:
                raise ValueError(
                    f"SLO {e['name']!r}: latency kind needs metric + threshold_ms"
                )
            out.append(SloObjective(
                name=str(e["name"]), kind="latency", target=target,
                metric=str(e["metric"]), threshold_ms=float(e["threshold_ms"]),
            ))
        elif kind == "error_rate":
            if not e.get("errors_metric") or not e.get("total_metric"):
                raise ValueError(
                    f"SLO {e['name']!r}: error_rate kind needs "
                    "errors_metric + total_metric"
                )
            out.append(SloObjective(
                name=str(e["name"]), kind="error_rate", target=target,
                errors_metric=str(e["errors_metric"]),
                total_metric=str(e["total_metric"]),
            ))
        else:
            raise ValueError(f"SLO {e['name']!r}: unknown kind {kind!r}")
    return out


def load_slo_config(source: str | None = None) -> list[SloObjective]:
    """SLO objectives from `source`, the ``BEE2BEE_SLO_CONFIG`` env var
    (inline JSON array, or a path to a JSON file), or the defaults."""
    data = load_json_source(source, "BEE2BEE_SLO_CONFIG", opener="[")
    if data is None:
        return parse_slo_config(DEFAULT_SLO_CONFIG)
    return parse_slo_config(data)


# burn-rate gauges (bee2bee_slo_* after prefixing): labeled by objective
# name — bounded by the configured objective list, not by request traffic
_G_SLO_BURN = get_registry().gauge(
    "slo.burn_rate", "error-budget burn rate by objective and window"
)
_G_SLO_STATUS = get_registry().gauge(
    "slo.status", "objective status: 0 ok, 1 burning, 2 tripped"
)
_G_SLO_BAD_FRACTION = get_registry().gauge(
    "slo.bad_fraction", "bad-event fraction over the fast window"
)

STATUS_OK = "ok"
STATUS_BURNING = "burning"
STATUS_TRIPPED = "tripped"
_STATUS_CODE = {STATUS_OK: 0, STATUS_BURNING: 1, STATUS_TRIPPED: 2}


class SloTracker:
    """Continuous multi-window burn-rate evaluation of SLO objectives
    against the (cumulative) local metrics registry.

    Each ``evaluate()`` snapshots every objective's cumulative (bad,
    total) event counts and computes the bad fraction over a FAST and a
    SLOW trailing window from snapshot deltas; burn rate is that fraction
    divided by the error budget (burn 1.0 = exactly spending the budget;
    the classic page condition is burn high in BOTH windows — fast for
    responsiveness, slow to ignore blips). A trip calls ``on_trip``
    (the flight recorder) at most once per ``trip_cooldown_s``."""

    def __init__(
        self,
        objectives: list[SloObjective] | None = None,
        registry: MetricsRegistry | None = None,
        fast_window_s: float = 300.0,
        slow_window_s: float = 3600.0,
        trip_burn_rate: float = 6.0,
        on_trip: Callable[[SloObjective, dict], None] | None = None,
        trip_cooldown_s: float = 300.0,
        clock: Clock | None = None,
    ):
        self.objectives = (
            list(objectives) if objectives is not None
            else parse_slo_config(DEFAULT_SLO_CONFIG)
        )
        self._reg = registry or get_registry()
        self._clock = resolve_clock(clock)
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.trip_burn_rate = trip_burn_rate
        self.on_trip = on_trip
        self.trip_cooldown_s = trip_cooldown_s
        self._lock = threading.Lock()
        self._snaps: dict[str, deque] = {
            o.name: deque() for o in self.objectives
        }
        self._last_trip: dict[str, float] = {}
        self._last_eval: list[dict] = []

    # ---- cumulative event counts

    def _counts(self, o: SloObjective) -> tuple[float, float]:
        """Cumulative (bad, total) event counts for an objective."""
        if o.kind == "latency":
            m = self._reg.get(o.metric)
            if not isinstance(m, Histogram):
                return 0.0, 0.0
            count, _ = m.totals()
            good = m.count_le(o.threshold_ms)
            # totals() and count_le() take the histogram lock separately:
            # an observe landing between them can make good > count for
            # one reading. bad is cumulative and monotone — clamp rather
            # than report a negative burn for a tick.
            return float(max(0, count - good)), float(count)
        errors = self._reg.get(o.errors_metric)
        total = self._reg.get(o.total_metric)
        bad = errors.total() if isinstance(errors, Counter) else 0.0
        tot = total.total() if isinstance(total, Counter) else 0.0
        return float(bad), float(tot)

    @staticmethod
    def _window_delta(snaps: deque, now: float, window_s: float) -> tuple[float, float]:
        """(bad, total) delta over the trailing window: latest snapshot
        minus the newest snapshot at/before the window start (or the
        oldest available — a partial window early in the process's life
        still reports, it just covers less time)."""
        if len(snaps) < 2:
            return 0.0, 0.0
        t_now, bad_now, tot_now = snaps[-1]
        start = now - window_s
        ref = snaps[0]
        for s in snaps:
            if s[0] <= start:
                ref = s
            else:
                break
        return bad_now - ref[1], tot_now - ref[2]

    def evaluate(self, now: float | None = None) -> list[dict]:
        """Snapshot + compute every objective; refresh the slo.* gauges;
        fire on_trip for fresh trips. Never throws (telemetry contract)."""
        try:
            return self._evaluate(now)
        except Exception:  # noqa: BLE001 — the health plane must not crash serving
            logger.exception("SLO evaluation failed")
            return self._last_eval

    def _evaluate(self, now: float | None) -> list[dict]:
        now = self._clock.time() if now is None else now
        out: list[dict] = []
        with self._lock:
            for o in self.objectives:
                bad, tot = self._counts(o)
                snaps = self._snaps[o.name]
                snaps.append((now, bad, tot))
                horizon = now - self.slow_window_s
                # keep ONE snapshot at/before the horizon as the slow
                # window's reference point
                while len(snaps) > 2 and snaps[1][0] <= horizon:
                    snaps.popleft()
                entry = {**o.describe()}
                burns = {}
                for label, win in (("fast", self.fast_window_s),
                                   ("slow", self.slow_window_s)):
                    dbad, dtot = self._window_delta(snaps, now, win)
                    frac = dbad / dtot if dtot > 0 else 0.0
                    burns[label] = {
                        "bad": dbad, "total": dtot,
                        "bad_fraction": round(frac, 6),
                        "burn_rate": round(frac / o.budget, 4),
                    }
                burn_fast = burns["fast"]["burn_rate"]
                burn_slow = burns["slow"]["burn_rate"]
                if (burn_fast >= self.trip_burn_rate
                        and burn_slow >= self.trip_burn_rate):
                    status = STATUS_TRIPPED
                elif burn_fast >= 1.0:
                    status = STATUS_BURNING
                else:
                    status = STATUS_OK
                entry.update(
                    windows=burns, status=status,
                    burn_rate_fast=burn_fast, burn_rate_slow=burn_slow,
                )
                _G_SLO_BURN.set(burn_fast, objective=o.name, window="fast")
                _G_SLO_BURN.set(burn_slow, objective=o.name, window="slow")
                _G_SLO_STATUS.set(_STATUS_CODE[status], objective=o.name)
                _G_SLO_BAD_FRACTION.set(
                    burns["fast"]["bad_fraction"], objective=o.name
                )
                if status == STATUS_TRIPPED:
                    last = self._last_trip.get(o.name, -math.inf)
                    if now - last >= self.trip_cooldown_s:
                        self._last_trip[o.name] = now
                        entry["tripped_at"] = now
                        if self.on_trip is not None:
                            try:
                                self.on_trip(o, dict(entry))
                            except Exception:  # noqa: BLE001
                                logger.exception("SLO on_trip hook failed")
                out.append(entry)
            self._last_eval = out
        return out

    def status(self) -> list[dict]:
        """A fresh evaluation (what ``GET /slo`` serves)."""
        return self.evaluate()

    def max_fast_burn(self) -> float:
        """Highest fast-window burn rate across objectives from the LAST
        evaluation (the monitor loop refreshes it on the ping cadence) —
        the shed signal the admission controller (router/admission.py)
        gates on. 0.0 before any evaluation: a node must not shed on no
        evidence."""
        return max(
            (float(e.get("burn_rate_fast") or 0.0) for e in self._last_eval),
            default=0.0,
        )

    def brief(self) -> dict:
        """Compact per-objective summary for the gossip digest."""
        out = {}
        for entry in self._last_eval:
            out[entry["name"]] = {
                "status": entry["status"],
                "burn_fast": entry["burn_rate_fast"],
                "burn_slow": entry["burn_rate_slow"],
            }
        return out


# --------------------------------------------------------- flight recorder


@dataclass
class _RingEvent:
    ts: float
    kind: str
    fields: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"ts": round(self.ts, 3), "kind": self.kind, **self.fields}


class FlightRecorder:
    """Bounded ring of recent telemetry events + on-disk incident bundles.

    ``record()`` is the cheap path (deque append under a lock, never
    throws) fed by span completions (tracing listener), notable frame ops
    (meshnet/node.py) and per-tick metric deltas (monitor loop).

    ``incident()`` is the expensive path, taken only on typed failures:
    it snapshots the ring, the metrics digest, and the stitched trace of
    the offending request into one JSON bundle under ``incident_dir``.
    The snapshot itself is in-memory and cheap; the DISK half (mkdir,
    write, prune) runs on a short-lived writer thread so callers on the
    asyncio event loop (gen_error serve path, pipeline failover, SLO
    trips from the monitor loop) never block mesh traffic on a slow
    filesystem — ``flush()`` joins outstanding writes (tests, shutdown).
    Per-kind cooldown bounds disk churn under a failure storm; bundles
    beyond ``max_incidents`` are pruned oldest-first."""

    def __init__(
        self,
        capacity: int = 512,
        incident_dir: str | Path | None = None,
        max_incidents: int = 32,
        cooldown_s: float = 30.0,
    ):
        self._events: deque[_RingEvent] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._incident_dir = Path(incident_dir) if incident_dir else None
        self.max_incidents = max_incidents
        self.cooldown_s = cooldown_s
        self._last_incident: dict[str, float] = {}  # kind -> ts
        self._disk_lock = threading.Lock()  # serializes write + prune
        self._writers: list[threading.Thread] = []
        # header index cache for list_incidents: path -> (stat sig, header)
        self._index_cache: dict[str, tuple[tuple, dict]] = {}

    # ---- configuration

    @property
    def incident_dir(self) -> Path:
        """Resolved lazily: env ``BEE2BEE_INCIDENT_DIR``, else
        ``<bee2bee home>/incidents`` (home itself is env-overridable)."""
        if self._incident_dir is None:
            env = os.environ.get("BEE2BEE_INCIDENT_DIR")
            self._incident_dir = (
                Path(env) if env else bee2bee_home() / "incidents"
            )
        return self._incident_dir

    @incident_dir.setter
    def incident_dir(self, value: str | Path | None) -> None:
        self._incident_dir = Path(value) if value else None

    # ---- ring

    def record(self, kind: str, **fields) -> None:
        """Append one ring event; never throws."""
        try:
            with self._lock:
                # the recorder is process-global and may outlive any one
                # clock installation — resolve at call time, not __init__
                self._events.append(
                    _RingEvent(get_clock().time(), str(kind), fields)
                )
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    def events(self, limit: int = 200) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        return [e.to_dict() for e in evs[-limit:]]

    def clear(self) -> None:
        """Tests: reset ring + cooldowns (disk bundles stay)."""
        with self._lock:
            self._events.clear()
            self._last_incident.clear()

    # ---- incidents

    def incident(
        self,
        kind: str,
        detail: str = "",
        trace_id: str | None = None,
        node: str | None = None,
        extra: dict | None = None,
    ) -> str | None:
        """Snapshot an incident bundle. Returns the incident id, or None
        when suppressed by the per-kind cooldown (or when the snapshot
        itself fails). The bundle is captured in-memory HERE — ring, trace
        and digest reflect this instant — but the disk write happens on a
        writer thread (``flush()`` waits for it): callers sit on the
        asyncio event loop and must not block on a slow filesystem. A
        failed write costs the bundle, never serving — best-effort by
        contract."""
        try:
            now = get_clock().time()
            with self._lock:
                last = self._last_incident.get(kind, -math.inf)
                if now - last < self.cooldown_s:
                    return None
                self._last_incident[kind] = now
            if trace_id is None:
                ctx = current_trace_ctx()
                trace_id = ctx.trace_id if ctx else None
            inc_id = new_id("inc")
            bundle: dict[str, Any] = {
                "id": inc_id,
                "ts": now,
                "kind": kind,
                "detail": detail,
                "node": node,
                "trace_id": trace_id,
                "events": self.events(limit=self._events.maxlen or 512),
                "metrics": build_digest(),
            }
            if extra:
                bundle["extra"] = extra
            if trace_id:
                # the stitched trace of the offending request: in a
                # one-node-per-process deployment this is the local
                # fragment (peers' fragments stitch on read via /trace);
                # in loopback meshes the shared tracer holds every hop
                bundle["trace"] = stitch_trace([
                    {"node": node, "spans": get_tracer().for_trace(trace_id)}
                ])
            self.record("incident", id=inc_id, incident_kind=kind, detail=detail)
            payload = json.dumps(bundle, default=str)
            t = threading.Thread(
                target=self._write_bundle, args=(inc_id, kind, detail, payload),
                name=f"incident-write-{inc_id}", daemon=True,
            )
            with self._lock:
                self._writers = [w for w in self._writers if w.is_alive()]
                self._writers.append(t)
            t.start()
            return inc_id
        except Exception:  # noqa: BLE001 — telemetry never throws
            logger.exception("incident snapshot failed")
            return None

    def flush(self, timeout_s: float = 5.0) -> None:
        """Join outstanding bundle writes (tests, orderly shutdown)."""
        # writer threads live in REAL time: joining them against a virtual
        # deadline would mis-compute the remaining wait under a sim clock
        deadline = time.time() + timeout_s  # meshlint: ignore[ML-C001] -- real thread-join deadline
        with self._lock:
            writers = list(self._writers)
        for w in writers:
            w.join(max(0.0, deadline - time.time()))  # meshlint: ignore[ML-C001] -- real thread-join deadline

    def _write_bundle(self, inc_id: str, kind: str, detail: str, payload: str) -> None:
        try:
            with self._disk_lock:
                d = self.incident_dir
                d.mkdir(parents=True, exist_ok=True)
                path = d / f"{inc_id}.json"
                path.write_text(payload)
                self._prune(d)
            logger.warning("incident %s (%s): %s -> %s", inc_id, kind, detail, path)
        except Exception:  # noqa: BLE001 — a full disk must not take down serving
            logger.exception("incident write failed (%s)", inc_id)

    def _prune(self, d: Path) -> None:
        bundles = sorted(d.glob("inc-*.json"), key=lambda p: p.stat().st_mtime)
        for p in bundles[: max(0, len(bundles) - self.max_incidents)]:
            try:
                p.unlink()
            except OSError:
                pass

    def list_incidents(self) -> list[dict]:
        """Newest-first header index of on-disk bundles (id, ts, kind,
        detail, node, trace_id) — the ``GET /debug/incidents`` listing.
        Headers are cached per (path, stat signature): polling the debug
        surface re-parses only bundles that actually changed, not every
        multi-hundred-KB ring+trace payload on each request."""
        try:
            d = self.incident_dir
            if not d.is_dir():
                return []
            out = []
            seen_paths: set[str] = set()
            for p in sorted(d.glob("inc-*.json"),
                            key=lambda p: p.stat().st_mtime, reverse=True):
                key = str(p)
                seen_paths.add(key)
                try:
                    st = p.stat()
                    sig = (st.st_mtime_ns, st.st_size)
                    cached = self._index_cache.get(key)
                    if cached and cached[0] == sig:
                        out.append(dict(cached[1]))
                        continue
                    b = json.loads(p.read_text())
                except (OSError, ValueError):
                    continue
                header = {
                    k: b.get(k)
                    for k in ("id", "ts", "kind", "detail", "node", "trace_id")
                }
                self._index_cache[key] = (sig, header)
                out.append(dict(header))
            for key in list(self._index_cache):
                if key not in seen_paths:  # pruned/removed bundles
                    self._index_cache.pop(key, None)
            return out
        except Exception:  # noqa: BLE001
            logger.exception("incident listing failed")
            return []

    def load_incident(self, incident_id: str) -> dict | None:
        """Full bundle by id; None when unknown. The id is user input off
        a URL — resolve by exact-match listing, never by path join."""
        try:
            d = self.incident_dir
            if not d.is_dir():
                return None
            for p in d.glob("inc-*.json"):
                if p.stem == incident_id:
                    return json.loads(p.read_text())
            return None
        except Exception:  # noqa: BLE001
            logger.exception("incident load failed")
            return None


_RECORDER = FlightRecorder()
_LISTENER_WIRED = False


def _span_listener(span) -> None:
    """Tracing listener: every completed span becomes a compact ring
    event — the 'what just happened' half of an incident bundle."""
    _RECORDER.record(
        "span",
        name=span.name,
        duration_ms=round(span.duration_ms, 3),
        trace_id=span.trace_id,
        error=span.error,
    )


def get_recorder() -> FlightRecorder:
    """The process-global flight recorder (wired to the global tracer on
    first use, so span completions start landing in the ring)."""
    global _LISTENER_WIRED
    if not _LISTENER_WIRED:
        _LISTENER_WIRED = True
        get_tracer().add_listener(_span_listener)
    return _RECORDER
