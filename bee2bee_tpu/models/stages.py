"""Pipeline stages: layer-range partial models for cross-peer serving.

The reference's embryonic PP seed builds a DistilBERT partial (embeddings
if first stage, encoder layers [start, end) — reference hf.py:180-205) and
forwards hidden states between workers over the wire (reference
node.py:236-277, kinds hf_part_load/hf_part_forward). This module is the
TPU-native generalization for BASELINE config 4 (zephyr-7b split across
two peers):

- Stage s of S owns transformer layers [a, b) of the stacked [L, ...]
  param tree (a contiguous slice of every layer-stacked leaf — no pytree
  surgery, the layout was designed for this), plus the embedding if s == 0
  and final-norm + LM head if s == S-1.
- `stage_forward` runs ids (first stage) or a hidden-state chunk through
  the slice against a per-stage KV cache at a given offset — the same
  static-shape cached contract as core.forward, so prefill (T=bucket) and
  decode (T=1) reuse one compiled program per shape.
- Hidden states cross peer boundaries as [B, T, D] tensors in binary
  frames (protocol.encode_binary) — ~2 bytes/element bf16 rather than the
  reference's JSON float lists (~5x the bytes, node.py:96-98).

Per-stage memory: a stage holds (b - a)/L of the params and of the KV
cache — two v5e-8 hosts each hold half of zephyr-7b.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from . import core
from .config import ModelConfig

Params = dict[str, Any]


def layer_ranges(n_layers: int, n_stages: int) -> list[tuple[int, int]]:
    """Contiguous [a, b) per stage; remainders spread to the EARLY stages
    (the first stage also pays the embedding, but early stages finish
    earlier in the 1F1B schedule, so front-loading balances the bubble)."""
    if not 1 <= n_stages <= n_layers:
        raise ValueError(f"n_stages={n_stages} must be in [1, {n_layers}]")
    base, extra = divmod(n_layers, n_stages)
    out, a = [], 0
    for s in range(n_stages):
        b = a + base + (1 if s < extra else 0)
        out.append((a, b))
        a = b
    return out


@dataclass(frozen=True)
class StageSpec:
    n_stages: int
    stage: int  # 0-based
    start: int  # first layer (inclusive)
    end: int  # last layer (exclusive)

    @property
    def is_first(self) -> bool:
        return self.stage == 0

    @property
    def is_last(self) -> bool:
        return self.stage == self.n_stages - 1

    @classmethod
    def build(cls, cfg: ModelConfig, n_stages: int, stage: int) -> "StageSpec":
        if not 0 <= stage < n_stages:
            raise ValueError(f"stage={stage} must be in [0, {n_stages})")
        a, b = layer_ranges(cfg.n_layers, n_stages)[stage]
        return cls(n_stages=n_stages, stage=stage, start=a, end=b)


def extract_stage_params(params: Params, cfg: ModelConfig, spec: StageSpec) -> Params:
    """Slice the full param tree down to one stage's share.

    Layer-stacked leaves ([L, ...]) keep rows [start, end); the embedding
    (+ learned pos) stays only on the first stage; final_norm + lm_head
    only on the last. Tied embeddings force tok_embed onto the last stage
    too (it IS the output head there)."""
    out: Params = {
        "layers": jax.tree.map(lambda a: a[spec.start : spec.end], params["layers"])
    }
    if spec.is_first:
        out["tok_embed"] = params["tok_embed"]
        if "pos_embed" in params:
            out["pos_embed"] = params["pos_embed"]
        if "embed_norm" in params:  # bloom's embedding LayerNorm
            out["embed_norm"] = params["embed_norm"]
    if spec.is_last:
        out["final_norm"] = params["final_norm"]
        if cfg.tie_embeddings:
            out["tok_embed"] = params["tok_embed"]
        elif "lm_head" in params:
            out["lm_head"] = params["lm_head"]
            if "lm_head_bias" in params:  # phi: untied head carries a bias
                out["lm_head_bias"] = params["lm_head_bias"]
    return out


def init_stage_cache(
    cfg: ModelConfig, spec: StageSpec, batch: int, max_len: int, dtype=jnp.bfloat16
):
    """KV cache for this stage's layers only: [end-start, B, S, Hkv, hd]."""
    shape = (spec.end - spec.start, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def stage_forward(
    sparams: Params,
    cfg: ModelConfig,
    spec: StageSpec,
    x,  # [B, T] int32 ids (first stage) | [B, T, D] hidden (later stages)
    cache,  # init_stage_cache pytree or None (uncached full forward)
    offset,  # [] or [B] int32 write position, as core.forward
    write_mask=None,  # [B] bool: rows whose cache this call may write
):
    """Run one stage. Returns (out, new_cache) where out is logits
    [B, T, V] on the last stage and hidden [B, T, D] otherwise.

    Mirrors core.forward's cache/mask semantics exactly — a chain of
    stage_forward calls over all stages is numerically identical to one
    core.forward (test_stages asserts this).

    `write_mask` enables continuous batching across the wire: a new
    request prefills into ITS row of a shared [B]-row session cache while
    the other rows' K/V stay untouched (their outputs for this call are
    discarded by the caller). None means write every row."""
    if spec.is_first:
        B, T = x.shape
    else:
        B, T, _ = x.shape

    off = jnp.asarray(offset, jnp.int32)
    off_b = jnp.broadcast_to(off.reshape(-1), (B,))
    positions = off_b[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]

    if spec.is_first:
        h = core.embed_tokens(sparams, cfg, x, positions)
    else:
        h = x

    S = cache["k"].shape[2] if cache is not None else None
    # gemma-2 alternation by GLOBAL layer index (spec.start + local idx):
    # the split model must window exactly the layers the monolith windows
    layer_mask = core.make_layer_mask(cfg, positions, T, S, start=spec.start)

    def rope_flag(idx):
        if cfg.local_rope_theta is None:
            return None
        return core.is_sliding_layer(cfg, spec.start + idx)

    def layer(carry, xs):
        h, ck, cv = carry
        lp, idx = xs
        if ck is None:
            return (
                core.transformer_block(lp, cfg, h, positions,
                                       layer_mask(idx),
                                       rope_local=rope_flag(idx)),
                None,
                None,
            ), None

        def kv_hook(k, v):
            nonlocal ck, cv

            def write(row, new, start, keep):
                upd = lax.dynamic_update_slice(
                    row, new.astype(row.dtype), (start, 0, 0)
                )
                return jnp.where(keep, upd, row)

            keep_b = (
                jnp.ones((B,), bool)
                if write_mask is None
                else jnp.asarray(write_mask, bool)
            )
            wk = jax.vmap(write)(ck[idx], k, off_b, keep_b)
            wv = jax.vmap(write)(cv[idx], v, off_b, keep_b)
            ck = ck.at[idx].set(wk)
            cv = cv.at[idx].set(wv)
            return wk, wv

        h = core.transformer_block(lp, cfg, h, positions, layer_mask(idx),
                                   kv_hook=kv_hook,
                                   rope_local=rope_flag(idx))
        return (h, ck, cv), None

    n_local = spec.end - spec.start
    layer_params = sparams["layers"]
    if isinstance(layer_params, (list, tuple)):
        # Unstacked per-layer trees: unrolled loop (the CPU fast path —
        # XLA:CPU can't pre-pack GEMM operands sliced in-graph from the
        # stacked arrays; see core.forward / docs/PERF.md "CPU fallback").
        # The same `layer` body runs with a static layer index.
        carry = (h, cache["k"], cache["v"]) if cache is not None else (h, None, None)
        for i, lp in enumerate(layer_params):
            carry, _ = layer(carry, (lp, i))
        h, ck, cv = carry
        new_cache = {"k": ck, "v": cv} if cache is not None else None
    elif cache is not None:
        xs = (layer_params, jnp.arange(n_local))
        (h, ck, cv), _ = lax.scan(layer, (h, cache["k"], cache["v"]), xs)
        new_cache = {"k": ck, "v": cv}
    else:
        xs = (layer_params, jnp.arange(n_local))
        (h, _, _), _ = lax.scan(layer, (h, None, None), xs)
        new_cache = None

    if spec.is_last:
        return core.final_logits(sparams, cfg, h), new_cache
    return h, new_cache
