"""Weight-only int8 quantization for serving.

Decode is HBM-bandwidth-bound on the WEIGHTS (every step streams all of
them); storing the big matmul weights as int8 + per-output-channel f32
scales halves that traffic vs bf16 — the standard weight-only quant
recipe, with no quality-relevant change to activations (which stay
bf16/f32). The reference has no quantization story at all.

Representation: a quantized weight is the subtree {"q": int8 [..., in,
out], "s": f32 [..., out]} in place of the dense array. Per-OUT-channel
scales commute with the matmul — (x @ q) * s == x @ (q * s) — so
core.matmul dequantizes AFTER the dot and XLA fuses the int8->bf16
convert into the dot's operand read (weights leave HBM as int8).

What quantizes: attention projections (wq/wk/wv/wo), dense-MLP weights
(w_up/w_gate/w_down), and MoE EXPERT weights (moe/w_up|w_gate|w_down,
[L, E, in, out] with per-expert per-out-channel scales [L, E, out] —
for Mixtral-class models the experts ARE the weights, so int8 halves
almost all of decode's HBM traffic; core.expert_einsum applies the
scales after the contraction). Embeddings (gather, often tied to the
LM head), norms, biases, and the tiny MoE router stay dense.

Engine flag: EngineConfig(quantize="int8") / BEE2BEE_QUANTIZE=int8.
Partition rules treat {"q","s"} transparently (models/partition strips
the /q and /s path suffixes; scales shard like the weight's out axis).
"""

from __future__ import annotations

import numpy as np

# path suffixes (models/partition path convention) that quantize
QUANT_SUFFIXES = (
    "attn/wq", "attn/wk", "attn/wv", "attn/wo",
    "mlp/w_up", "mlp/w_gate", "mlp/w_down",
    "moe/w_up", "moe/w_gate", "moe/w_down",  # per-expert scales
)


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def quantize_weight(w: np.ndarray) -> dict:
    """[..., in, out] float -> {"q": int8 same shape, "s": f32 [..., out]}
    with symmetric per-out-channel scales (amax over the in dim)."""
    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=-2)  # [..., out]
    s = (amax / 127.0).astype(np.float32)
    safe = np.where(s == 0.0, 1.0, s)
    q = np.clip(np.rint(w / safe[..., None, :]), -127, 127).astype(np.int8)
    return {"q": q, "s": s}


def dequantize_weight(qw: dict) -> np.ndarray:
    return qw["q"].astype(np.float32) * qw["s"][..., None, :]


def quantize_params(params: dict) -> dict:
    """Return a copy of the param tree with QUANT_SUFFIXES weights
    replaced by {"q","s"} subtrees (host-side numpy — runs before
    shard_params so devices only ever see int8)."""

    def walk(node, path=""):
        if isinstance(node, dict):
            return {k: walk(v, f"{path}/{k}" if path else k) for k, v in node.items()}
        if path.endswith(QUANT_SUFFIXES):
            return quantize_weight(np.asarray(node))
        return node

    return walk(params)
