"""Checkpoint export: our param pytree → standard interchange formats.

The TPU-native analogue of the reference's model-export surface
(reference hf.py:139-158 exports TorchScript and ONNX). Torch graph
formats make no sense for a jax/XLA stack, so the interchange story is:

- **HF-layout safetensors** (`export_hf`): the exact inverse of
  models/loader's name mapping, plus a matching HF ``config.json`` — any
  torch/transformers stack loads the result with ``from_pretrained``.
  Covers the GPT-2, Llama/Mistral/Mixtral/Gemma, Phi, and GPT-NeoX families, like the
  loader.
- **Native piece format** (loader.save_native): content-addressed shard
  pieces + manifest — the mesh-distribution and checkpoint/resume format.

Everything is offline and torch-free: safetensors files are written with
numpy (bf16 via the uint16 bit pattern, mirroring the loader's reader).
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import numpy as np

from .config import ModelConfig

_DTYPE_NAMES = {
    "float32": "F32",
    "float16": "F16",
    "bfloat16": "BF16",
    "int64": "I64",
    "int32": "I32",
    "uint8": "U8",
    "bool": "BOOL",
}


def write_safetensors(path: str | Path, tensors: dict[str, np.ndarray],
                      metadata: dict[str, str] | None = None) -> None:
    """Minimal safetensors writer (header JSON + raw buffers) — the inverse
    of loader._read_safetensors, same no-torch rationale."""
    header: dict = {}
    if metadata:
        header["__metadata__"] = metadata
    bufs: list[bytes] = []
    offset = 0
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        dt = _DTYPE_NAMES.get(arr.dtype.name)
        if dt is None:
            raise ValueError(f"unsupported export dtype {arr.dtype} for {name!r}")
        buf = (
            arr.view(np.uint16).tobytes() if dt == "BF16" else arr.tobytes()
        )
        header[name] = {
            "dtype": dt,
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + len(buf)],
        }
        bufs.append(buf)
        offset += len(buf)
    blob = json.dumps(header).encode("utf-8")
    with open(path, "wb") as f:
        f.write(len(blob).to_bytes(8, "little"))
        f.write(blob)
        for buf in bufs:
            f.write(buf)


def _np(x, dtype=None) -> np.ndarray:
    arr = np.asarray(jax.device_get(x))
    if dtype is not None:
        arr = arr.astype(dtype)
    return np.ascontiguousarray(arr)


def _export_gpt2_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_gpt2: unstack layers, re-fuse q/k/v into
    the HF c_attn block."""
    layers = params["layers"]
    state = {
        "transformer.wte.weight": _np(params["tok_embed"], dtype),
        "transformer.wpe.weight": _np(params["pos_embed"], dtype),
        "transformer.ln_f.weight": _np(params["final_norm"]["scale"], dtype),
        "transformer.ln_f.bias": _np(params["final_norm"]["bias"], dtype),
        # tied embeddings (gpt2 family always ties): transformers expects
        # the key to exist even though it shares storage with wte
        "lm_head.weight": _np(params["tok_embed"], dtype),
    }
    for i in range(cfg.n_layers):
        p = f"transformer.h.{i}."
        state[p + "ln_1.weight"] = _np(layers["ln1"]["scale"][i], dtype)
        state[p + "ln_1.bias"] = _np(layers["ln1"]["bias"][i], dtype)
        state[p + "ln_2.weight"] = _np(layers["ln2"]["scale"][i], dtype)
        state[p + "ln_2.bias"] = _np(layers["ln2"]["bias"][i], dtype)
        a = layers["attn"]
        state[p + "attn.c_attn.weight"] = np.concatenate(
            [_np(a["wq"][i], dtype), _np(a["wk"][i], dtype), _np(a["wv"][i], dtype)],
            axis=1,
        )
        state[p + "attn.c_attn.bias"] = np.concatenate(
            [_np(a["bq"][i], dtype), _np(a["bk"][i], dtype), _np(a["bv"][i], dtype)]
        )
        state[p + "attn.c_proj.weight"] = _np(a["wo"][i], dtype)
        state[p + "attn.c_proj.bias"] = _np(a["bo"][i], dtype)
        m = layers["mlp"]
        state[p + "mlp.c_fc.weight"] = _np(m["w_up"][i], dtype)
        state[p + "mlp.c_fc.bias"] = _np(m["b_up"][i], dtype)
        state[p + "mlp.c_proj.weight"] = _np(m["w_down"][i], dtype)
        state[p + "mlp.c_proj.bias"] = _np(m["b_down"][i], dtype)
    return state


def _export_bigcode_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_bigcode: nn.Linear [out, in] with the
    fused c_attn packing q block then k then v on the OUT dim."""
    layers = params["layers"]
    t = lambda a: _np(a, dtype).T
    state = {
        "transformer.wte.weight": _np(params["tok_embed"], dtype),
        "transformer.wpe.weight": _np(params["pos_embed"], dtype),
        "transformer.ln_f.weight": _np(params["final_norm"]["scale"], dtype),
        "transformer.ln_f.bias": _np(params["final_norm"]["bias"], dtype),
        "lm_head.weight": (
            _np(params["tok_embed"], dtype) if cfg.tie_embeddings
            else t(params["lm_head"])
        ),
    }
    a = layers["attn"]
    for i in range(cfg.n_layers):
        p = f"transformer.h.{i}."
        for ln, hf in (("ln1", "ln_1"), ("ln2", "ln_2")):
            state[p + f"{hf}.weight"] = _np(layers[ln]["scale"][i], dtype)
            state[p + f"{hf}.bias"] = _np(layers[ln]["bias"][i], dtype)
        state[p + "attn.c_attn.weight"] = np.concatenate(
            [t(a["wq"][i]), t(a["wk"][i]), t(a["wv"][i])], axis=0
        )
        state[p + "attn.c_attn.bias"] = np.concatenate(
            [_np(a[b][i], dtype) for b in ("bq", "bk", "bv")]
        )
        state[p + "attn.c_proj.weight"] = t(a["wo"][i])
        state[p + "attn.c_proj.bias"] = _np(a["bo"][i], dtype)
        m = layers["mlp"]
        state[p + "mlp.c_fc.weight"] = t(m["w_up"][i])
        state[p + "mlp.c_fc.bias"] = _np(m["b_up"][i], dtype)
        state[p + "mlp.c_proj.weight"] = t(m["w_down"][i])
        state[p + "mlp.c_proj.bias"] = _np(m["b_down"][i], dtype)
    return state


def _export_llama_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_llama: transpose back to HF [out, in] and
    undo the gemma (1 + w) rmsnorm fold."""
    layers = params["layers"]
    off = 1.0 if cfg.norm_plus_one else 0.0
    t = lambda a: _np(a, dtype).T
    norm = lambda a: _np(np.asarray(jax.device_get(a), np.float32) - off, dtype)
    state = {
        "model.embed_tokens.weight": _np(params["tok_embed"], dtype),
        "model.norm.weight": norm(params["final_norm"]["scale"]),
    }
    if "bias" in params["final_norm"]:  # stablelm: biased layernorms
        state["model.norm.bias"] = _np(params["final_norm"]["bias"], dtype)
    if not cfg.tie_embeddings:
        state["lm_head.weight"] = t(params["lm_head"])
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        if cfg.no_pre_norms:  # olmo2: output norms only
            state[p + "post_attention_layernorm.weight"] = norm(layers["ln1_post"]["scale"][i])
            state[p + "post_feedforward_layernorm.weight"] = norm(layers["ln2_post"]["scale"][i])
        elif cfg.post_norms:  # gemma-2 norm names (see loader._convert_llama)
            state[p + "input_layernorm.weight"] = norm(layers["ln1"]["scale"][i])
            state[p + "post_attention_layernorm.weight"] = norm(layers["ln1_post"]["scale"][i])
            state[p + "pre_feedforward_layernorm.weight"] = norm(layers["ln2"]["scale"][i])
            state[p + "post_feedforward_layernorm.weight"] = norm(layers["ln2_post"]["scale"][i])
        else:
            state[p + "input_layernorm.weight"] = norm(layers["ln1"]["scale"][i])
            state[p + "post_attention_layernorm.weight"] = norm(layers["ln2"]["scale"][i])
        if "ln1" in layers and "bias" in layers["ln1"]:  # stablelm: biased LNs
            state[p + "input_layernorm.bias"] = _np(layers["ln1"]["bias"][i], dtype)
            state[p + "post_attention_layernorm.bias"] = _np(layers["ln2"]["bias"][i], dtype)
        a = layers["attn"]
        for ours, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj"), ("wo", "o_proj")):
            state[p + f"self_attn.{hf}.weight"] = t(a[ours][i])
        if "bq" in a:  # qwen2: q/k/v-only bias
            for ours, hf in (("bq", "q_proj"), ("bk", "k_proj"), ("bv", "v_proj")):
                state[p + f"self_attn.{hf}.bias"] = _np(a[ours][i], dtype)
        if "q_norm" in a:  # qwen3/gemma3: per-head q/k RMSNorm scales
            # (gemma-3 stores them zero-centered — undo the (1+w) fold)
            state[p + "self_attn.q_norm.weight"] = norm(a["q_norm"][i])
            state[p + "self_attn.k_norm.weight"] = norm(a["k_norm"][i])
        if cfg.is_moe:
            moe = layers["moe"]
            if "q_norm" in a:  # qwen3_moe names
                state[p + "mlp.gate.weight"] = t(moe["router"][i])
                for e in range(cfg.n_experts):
                    q = p + f"mlp.experts.{e}."
                    state[q + "gate_proj.weight"] = t(moe["w_gate"][i][e])
                    state[q + "down_proj.weight"] = t(moe["w_down"][i][e])
                    state[q + "up_proj.weight"] = t(moe["w_up"][i][e])
            else:  # mixtral names
                state[p + "block_sparse_moe.gate.weight"] = t(moe["router"][i])
                for e in range(cfg.n_experts):
                    q = p + f"block_sparse_moe.experts.{e}."
                    state[q + "w1.weight"] = t(moe["w_gate"][i][e])
                    state[q + "w2.weight"] = t(moe["w_down"][i][e])
                    state[q + "w3.weight"] = t(moe["w_up"][i][e])
        else:
            m = layers["mlp"]
            state[p + "mlp.gate_proj.weight"] = t(m["w_gate"][i])
            state[p + "mlp.up_proj.weight"] = t(m["w_up"][i])
            state[p + "mlp.down_proj.weight"] = t(m["w_down"][i])
    return state


def _export_phi_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_phi."""
    layers = params["layers"]
    t = lambda a: _np(a, dtype).T
    state = {
        "model.embed_tokens.weight": _np(params["tok_embed"], dtype),
        "model.final_layernorm.weight": _np(params["final_norm"]["scale"], dtype),
        "model.final_layernorm.bias": _np(params["final_norm"]["bias"], dtype),
        "lm_head.weight": t(params["lm_head"]),
        "lm_head.bias": _np(params["lm_head_bias"], dtype),
    }
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        state[p + "input_layernorm.weight"] = _np(layers["ln1"]["scale"][i], dtype)
        state[p + "input_layernorm.bias"] = _np(layers["ln1"]["bias"][i], dtype)
        a = layers["attn"]
        for ours, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj"), ("wo", "dense")):
            state[p + f"self_attn.{hf}.weight"] = t(a[ours][i])
        for ours, hf in (("bq", "q_proj"), ("bk", "k_proj"), ("bv", "v_proj"), ("bo", "dense")):
            state[p + f"self_attn.{hf}.bias"] = _np(a[ours][i], dtype)
        m = layers["mlp"]
        state[p + "mlp.fc1.weight"] = t(m["w_up"][i])
        state[p + "mlp.fc1.bias"] = _np(m["b_up"][i], dtype)
        state[p + "mlp.fc2.weight"] = t(m["w_down"][i])
        state[p + "mlp.fc2.bias"] = _np(m["b_down"][i], dtype)
    return state


def _export_neox_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_neox (re-interleaves the fused QKV)."""
    layers = params["layers"]
    t = lambda a: _np(a, dtype).T
    H, hd, D = cfg.n_heads, cfg.head_dim, cfg.d_model
    state = {
        "gpt_neox.embed_in.weight": _np(params["tok_embed"], dtype),
        "gpt_neox.final_layer_norm.weight": _np(params["final_norm"]["scale"], dtype),
        "gpt_neox.final_layer_norm.bias": _np(params["final_norm"]["bias"], dtype),
        "embed_out.weight": t(params["lm_head"]),
    }
    a = layers["attn"]
    for i in range(cfg.n_layers):
        p = f"gpt_neox.layers.{i}."
        for ln, hf in (("ln1", "input_layernorm"), ("ln2", "post_attention_layernorm")):
            state[p + f"{hf}.weight"] = _np(layers[ln]["scale"][i], dtype)
            state[p + f"{hf}.bias"] = _np(layers[ln]["bias"][i], dtype)
        # ours [D, H*hd] -> HF fused [H, 3, hd, D] -> [3D, D]
        w3 = np.stack(
            [_np(a[k][i], dtype).T.reshape(H, hd, D) for k in ("wq", "wk", "wv")],
            axis=1,
        )
        b3 = np.stack(
            [_np(a[k][i], dtype).reshape(H, hd) for k in ("bq", "bk", "bv")],
            axis=1,
        )
        state[p + "attention.query_key_value.weight"] = w3.reshape(3 * D, D)
        state[p + "attention.query_key_value.bias"] = b3.reshape(3 * D)
        state[p + "attention.dense.weight"] = t(a["wo"][i])
        state[p + "attention.dense.bias"] = _np(a["bo"][i], dtype)
        m = layers["mlp"]
        state[p + "mlp.dense_h_to_4h.weight"] = t(m["w_up"][i])
        state[p + "mlp.dense_h_to_4h.bias"] = _np(m["b_up"][i], dtype)
        state[p + "mlp.dense_4h_to_h.weight"] = t(m["w_down"][i])
        state[p + "mlp.dense_4h_to_h.bias"] = _np(m["b_down"][i], dtype)
    return state


def _export_mpt_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_mpt (re-fuses the plain-thirds Wqkv)."""
    layers = params["layers"]
    t = lambda a: _np(a, dtype).T
    state = {
        "transformer.wte.weight": _np(params["tok_embed"], dtype),
        "transformer.norm_f.weight": _np(params["final_norm"]["scale"], dtype),
        "lm_head.weight": (
            _np(params["tok_embed"], dtype) if cfg.tie_embeddings
            else t(params["lm_head"])
        ),
    }
    a = layers["attn"]
    for i in range(cfg.n_layers):
        p = f"transformer.blocks.{i}."
        state[p + "norm_1.weight"] = _np(layers["ln1"]["scale"][i], dtype)
        state[p + "norm_2.weight"] = _np(layers["ln2"]["scale"][i], dtype)
        state[p + "attn.Wqkv.weight"] = np.concatenate(
            [t(a[k][i]) for k in ("wq", "wk", "wv")], axis=0
        )
        state[p + "attn.out_proj.weight"] = t(a["wo"][i])
        m = layers["mlp"]
        state[p + "ffn.up_proj.weight"] = t(m["w_up"][i])
        state[p + "ffn.down_proj.weight"] = t(m["w_down"][i])
    return state


def _export_bloom_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_bloom (re-interleaves the biased fused
    QKV per head, restores the embedding LayerNorm)."""
    layers = params["layers"]
    t = lambda a: _np(a, dtype).T
    H, hd, D = cfg.n_heads, cfg.head_dim, cfg.d_model
    state = {
        "transformer.word_embeddings.weight": _np(params["tok_embed"], dtype),
        "transformer.word_embeddings_layernorm.weight": _np(
            params["embed_norm"]["scale"], dtype),
        "transformer.word_embeddings_layernorm.bias": _np(
            params["embed_norm"]["bias"], dtype),
        "transformer.ln_f.weight": _np(params["final_norm"]["scale"], dtype),
        "transformer.ln_f.bias": _np(params["final_norm"]["bias"], dtype),
        "lm_head.weight": (
            _np(params["tok_embed"], dtype) if cfg.tie_embeddings
            else t(params["lm_head"])
        ),
    }
    a = layers["attn"]
    for i in range(cfg.n_layers):
        p = f"transformer.h.{i}."
        for ln, hf in (("ln1", "input_layernorm"),
                       ("ln2", "post_attention_layernorm")):
            state[p + f"{hf}.weight"] = _np(layers[ln]["scale"][i], dtype)
            state[p + f"{hf}.bias"] = _np(layers[ln]["bias"][i], dtype)
        w3 = np.stack(
            [_np(a[k][i], dtype).T.reshape(H, hd, D) for k in ("wq", "wk", "wv")],
            axis=1,
        )
        b3 = np.stack(
            [_np(a[k][i], dtype).reshape(H, hd) for k in ("bq", "bk", "bv")],
            axis=1,
        )
        state[p + "self_attention.query_key_value.weight"] = w3.reshape(3 * H * hd, D)
        state[p + "self_attention.query_key_value.bias"] = b3.reshape(3 * H * hd)
        state[p + "self_attention.dense.weight"] = t(a["wo"][i])
        state[p + "self_attention.dense.bias"] = _np(a["bo"][i], dtype)
        m = layers["mlp"]
        state[p + "mlp.dense_h_to_4h.weight"] = t(m["w_up"][i])
        state[p + "mlp.dense_h_to_4h.bias"] = _np(m["b_up"][i], dtype)
        state[p + "mlp.dense_4h_to_h.weight"] = t(m["w_down"][i])
        state[p + "mlp.dense_4h_to_h.bias"] = _np(m["b_down"][i], dtype)
    return state


def _export_falcon_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_falcon (re-fuses q/k/v: multi_query's
    q-block-then-kv rows for K=1, the per-head [H, 3, hd] interleave for
    K=H)."""
    layers = params["layers"]
    t = lambda a: _np(a, dtype).T
    H, K, hd, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_model
    state = {
        "transformer.word_embeddings.weight": _np(params["tok_embed"], dtype),
        "transformer.ln_f.weight": _np(params["final_norm"]["scale"], dtype),
        "transformer.ln_f.bias": _np(params["final_norm"]["bias"], dtype),
    }
    if cfg.tie_embeddings:
        state["lm_head.weight"] = _np(params["tok_embed"], dtype)
    else:
        state["lm_head.weight"] = t(params["lm_head"])
    a = layers["attn"]
    for i in range(cfg.n_layers):
        p = f"transformer.h.{i}."
        state[p + "input_layernorm.weight"] = _np(layers["ln1"]["scale"][i], dtype)
        state[p + "input_layernorm.bias"] = _np(layers["ln1"]["bias"][i], dtype)
        q, k, v = (t(a[key][i]) for key in ("wq", "wk", "wv"))
        if K == 1:
            fused = np.concatenate([q, k, v], axis=0)  # [(H+2)*hd, D]
        else:  # K == H: [H, 3, hd] out-dim interleave
            fused = np.stack(
                [w.reshape(H, hd, D) for w in (q, k, v)], axis=1
            ).reshape(3 * H * hd, D)
        state[p + "self_attention.query_key_value.weight"] = fused
        state[p + "self_attention.dense.weight"] = t(a["wo"][i])
        m = layers["mlp"]
        state[p + "mlp.dense_h_to_4h.weight"] = t(m["w_up"][i])
        state[p + "mlp.dense_4h_to_h.weight"] = t(m["w_down"][i])
    return state


def _export_gptj_state(params, cfg: ModelConfig, dtype) -> dict[str, np.ndarray]:
    """Inverse of loader._convert_gptj."""
    layers = params["layers"]
    t = lambda a: _np(a, dtype).T
    state = {
        "transformer.wte.weight": _np(params["tok_embed"], dtype),
        "transformer.ln_f.weight": _np(params["final_norm"]["scale"], dtype),
        "transformer.ln_f.bias": _np(params["final_norm"]["bias"], dtype),
        "lm_head.weight": t(params["lm_head"]),
        "lm_head.bias": _np(params["lm_head_bias"], dtype),
    }
    for i in range(cfg.n_layers):
        p = f"transformer.h.{i}."
        state[p + "ln_1.weight"] = _np(layers["ln1"]["scale"][i], dtype)
        state[p + "ln_1.bias"] = _np(layers["ln1"]["bias"][i], dtype)
        a = layers["attn"]
        for ours, hf in (("wq", "q_proj"), ("wk", "k_proj"),
                         ("wv", "v_proj"), ("wo", "out_proj")):
            state[p + f"attn.{hf}.weight"] = t(a[ours][i])
        m = layers["mlp"]
        state[p + "mlp.fc_in.weight"] = t(m["w_up"][i])
        state[p + "mlp.fc_in.bias"] = _np(m["b_up"][i], dtype)
        state[p + "mlp.fc_out.weight"] = t(m["w_down"][i])
        state[p + "mlp.fc_out.bias"] = _np(m["b_down"][i], dtype)
    return state


def hf_config_dict(cfg: ModelConfig, qkv_bias: bool | None = None,
                   qk_norm: bool | None = None) -> dict:
    """A transformers-compatible config.json for the exported checkpoint.

    `qkv_bias` overrides cfg.qkv_bias from the ACTUAL params ("bq" leaves
    present): a checkpoint loaded with biases under a biasless config must
    still export as qwen2, or transformers would silently drop the bias
    tensors the state dict carries."""
    if cfg.rope_scaling is not None and (cfg.pos_embedding != "rope"
                                         or cfg.parallel_block):
        # only the llama-branch config schema carries rope_scaling; any
        # other family would drop it on export and diverge in transformers
        raise ValueError(
            f"rope_scaling export is only supported for llama-branch "
            f"families; {cfg.name!r} would silently lose it"
        )
    if cfg.pos_embedding == "alibi" and not cfg.use_bias:  # mpt family
        H = cfg.n_heads
        if (cfg.n_kv_heads != H or (H & (H - 1)) or cfg.embedding_norm
                or cfg.norm != "layernorm" or cfg.norm_bias
                or cfg.activation != "gelu_exact"
                or cfg.d_ff != 4 * cfg.d_model):
            # transformers' MptMLP HARDCODES 4*hidden — any other ratio
            # would shape-mismatch (or silently re-init) on from_pretrained
            raise ValueError(
                "mpt export requires MHA with power-of-two heads, weight-"
                "only layernorms, no biases, exact gelu, and expansion "
                f"ratio 4 (transformers hardcodes it); got "
                f"kv={cfg.n_kv_heads}, heads={H}, act={cfg.activation!r}, "
                f"norm_bias={cfg.norm_bias}, d_ff={cfg.d_ff}"
            )
        return {
            "model_type": "mpt",
            "architectures": ["MptForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "d_model": cfg.d_model,
            "n_layers": cfg.n_layers,
            "n_heads": cfg.n_heads,
            "expansion_ratio": cfg.d_ff // cfg.d_model,
            "max_seq_len": cfg.max_seq_len,
            "no_bias": True,
            "layer_norm_epsilon": cfg.norm_eps,
            "attn_config": {"alibi": True},
            "tie_word_embeddings": cfg.tie_embeddings,
        }
    if cfg.pos_embedding == "alibi":  # bloom family
        if (cfg.n_kv_heads != cfg.n_heads or not cfg.use_bias
                or cfg.norm != "layernorm" or cfg.activation != "gelu"
                or not cfg.embedding_norm):
            # HF Bloom hardcodes MHA, biased linears, tanh gelu, and the
            # embedding LayerNorm — anything else would load in
            # transformers WITHOUT warning and silently diverge
            raise ValueError(
                "bloom export requires MHA, use_bias, layernorm, gelu, "
                f"and embedding_norm; got kv={cfg.n_kv_heads}, "
                f"act={cfg.activation!r}, bias={cfg.use_bias}, "
                f"norm={cfg.norm!r}, embedding_norm={cfg.embedding_norm}"
            )
        return {
            "model_type": "bloom",
            "architectures": ["BloomForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.d_model,
            "n_layer": cfg.n_layers,
            "n_head": cfg.n_heads,
            "layer_norm_epsilon": cfg.norm_eps,
            "apply_residual_connection_post_layernorm": False,
            "slow_but_exact": False,
            "tie_word_embeddings": cfg.tie_embeddings,
            # BloomConfig has no position-table size (ALiBi); the wild
            # checkpoints carry training length as seq_length — keep it
            # so the config round-trips
            "seq_length": cfg.max_seq_len,
        }
    if cfg.pos_embedding == "learned" and cfg.n_kv_heads != cfg.n_heads:
        # gpt-bigcode family (starcoder): the only learned-pos MQA layout
        if cfg.n_kv_heads != 1:
            raise ValueError(
                "gpt_bigcode export requires n_kv_heads=1 (multi_query); "
                f"got kv={cfg.n_kv_heads}"
            )
        # declare the gelu dialect the weights were trained with — a
        # hardcoded tanh-approx would load in transformers WITHOUT
        # warning and silently diverge for exact-gelu configs
        act = {"gelu": "gelu_pytorch_tanh", "gelu_exact": "gelu"}.get(cfg.activation)
        if act is None:
            raise ValueError(
                f"gpt_bigcode export supports gelu activations only; got "
                f"{cfg.activation!r}"
            )
        return {
            "model_type": "gpt_bigcode",
            "architectures": ["GPTBigCodeForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "n_positions": cfg.max_seq_len,
            "n_embd": cfg.d_model,
            "n_layer": cfg.n_layers,
            "n_head": cfg.n_heads,
            "n_inner": cfg.d_ff,
            "layer_norm_epsilon": cfg.norm_eps,
            "activation_function": act,
            "multi_query": True,
            "tie_word_embeddings": cfg.tie_embeddings,
        }
    if cfg.pos_embedding == "learned":  # gpt2 family
        return {
            "model_type": "gpt2",
            "architectures": ["GPT2LMHeadModel"],
            "vocab_size": cfg.vocab_size,
            "n_positions": cfg.max_seq_len,
            "n_embd": cfg.d_model,
            "n_layer": cfg.n_layers,
            "n_head": cfg.n_heads,
            "n_inner": cfg.d_ff,
            "layer_norm_epsilon": cfg.norm_eps,
            "tie_word_embeddings": True,
        }
    if cfg.parallel_block and cfg.rope_style == "interleaved":  # gpt-j
        if cfg.rope_theta != 10000.0 or cfg.activation != "gelu":
            # HF's GPTJ hardcodes rotary base 10000 and gelu_new: a
            # checkpoint exported from an overridden config would load
            # in transformers WITHOUT warning and silently diverge
            raise ValueError(
                f"gpt-j export requires rope_theta=10000/activation='gelu' "
                f"(transformers hardcodes them); got theta={cfg.rope_theta}, "
                f"activation={cfg.activation!r}"
            )
        return {
            "model_type": "gptj",
            "architectures": ["GPTJForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "n_embd": cfg.d_model,
            "n_layer": cfg.n_layers,
            "n_head": cfg.n_heads,
            "n_inner": cfg.d_ff,
            "n_positions": cfg.max_seq_len,
            "rotary_dim": cfg.rotary_dim,
            "layer_norm_epsilon": cfg.norm_eps,
            "tie_word_embeddings": False,
            "activation_function": "gelu_new",
        }
    if cfg.parallel_block and cfg.parallel_norms == 2:  # gpt-neox family
        return {
            "model_type": "gpt_neox",
            "architectures": ["GPTNeoXForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.d_model,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "intermediate_size": cfg.d_ff,
            "max_position_embeddings": cfg.max_seq_len,
            "rotary_emb_base": cfg.rope_theta,
            "rotary_pct": cfg.rotary_pct,
            "layer_norm_eps": cfg.norm_eps,
            "use_parallel_residual": True,
            "tie_word_embeddings": False,
            "hidden_act": "gelu",
        }
    if cfg.parallel_block and not cfg.use_bias:  # falcon family (bias-free
        # parallel block sharing one layernorm; phi's block is biased)
        if (cfg.n_kv_heads not in (1, cfg.n_heads) or cfg.mlp_bias
                or cfg.lm_head_bias or cfg.activation != "gelu_exact"
                or cfg.rotary_pct < 1.0):
            # HF Falcon hardcodes full rotary + erf gelu and only speaks
            # the multi_query / per-head-interleave KV layouts — anything
            # else would load in transformers and silently diverge
            raise ValueError(
                "falcon export requires n_kv_heads in (1, n_heads), full "
                "rotary, gelu_exact, and no mlp/lm_head biases; got "
                f"kv={cfg.n_kv_heads}, act={cfg.activation!r}, "
                f"rotary_pct={cfg.rotary_pct}"
            )
        return {
            "model_type": "falcon",
            "architectures": ["FalconForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.d_model,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "ffn_hidden_size": cfg.d_ff,
            "max_position_embeddings": cfg.max_seq_len,
            "rope_theta": cfg.rope_theta,
            "layer_norm_epsilon": cfg.norm_eps,
            "multi_query": cfg.n_kv_heads == 1,
            "parallel_attn": True,
            "new_decoder_architecture": False,
            "alibi": False,
            "bias": False,
            "tie_word_embeddings": cfg.tie_embeddings,
            "activation": "gelu",
        }
    if cfg.parallel_block:  # phi family
        return {
            "model_type": "phi",
            "architectures": ["PhiForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.d_model,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "num_key_value_heads": cfg.n_kv_heads,
            "intermediate_size": cfg.d_ff,
            "max_position_embeddings": cfg.max_seq_len,
            "rope_theta": cfg.rope_theta,
            "layer_norm_eps": cfg.norm_eps,
            "partial_rotary_factor": cfg.rotary_pct,
            "tie_word_embeddings": False,
            "hidden_act": "gelu_new",
        }
    if cfg.no_pre_norms:  # olmo2: post-norm-only blocks
        if (cfg.norm != "rmsnorm" or cfg.activation != "silu"
                or not cfg.post_norms or not (cfg.qk_norm and cfg.qk_norm_full)
                or cfg.rotary_pct < 1.0 or cfg.sliding_window or cfg.is_moe
                or cfg.attn_logit_softcap or cfg.logits_softcap
                or cfg.norm_plus_one or cfg.attn_scale or cfg.use_bias
                or cfg.qkv_bias or cfg.embedding_scale or cfg.embedding_norm
                or cfg.head_dim != cfg.d_model // cfg.n_heads):
            # Olmo2ForCausalLM hardcodes all of these — anything else
            # would load in transformers WITHOUT warning and diverge
            raise ValueError(
                f"olmo2 export requires rmsnorm/silu/full rotary/full-width "
                f"qk-norm and no window/softcaps/moe ({cfg.name!r})"
            )
        out = {
            "model_type": "olmo2",
            "architectures": ["Olmo2ForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.d_model,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "num_key_value_heads": cfg.n_kv_heads,
            "intermediate_size": cfg.d_ff,
            "max_position_embeddings": cfg.max_seq_len,
            "rope_theta": cfg.rope_theta,
            "rms_norm_eps": cfg.norm_eps,
            "tie_word_embeddings": cfg.tie_embeddings,
        }
        if cfg.rope_scaling is not None:
            if cfg.rope_scaling[0] != "linear":
                raise ValueError("olmo2 export supports linear rope_scaling only")
            out["rope_scaling"] = {"rope_type": "linear",
                                   "factor": cfg.rope_scaling[1]}
        return out
    if cfg.norm == "layernorm":  # stablelm: the one llama-layout family
        # with biased LayerNorms (and a partial_rotary_factor field)
        if (cfg.norm_plus_one or cfg.is_moe or cfg.post_norms
                or cfg.qk_norm or cfg.sliding_window
                or cfg.activation != "silu" or cfg.rope_style != "half"
                or cfg.use_bias or cfg.mlp_bias or not cfg.norm_bias
                or cfg.embedding_scale or cfg.attn_logit_softcap
                or cfg.attn_scale or cfg.logits_softcap):
            # StableLmForCausalLM hardcodes silu / half rotary / biased
            # LNs with bias-free mlp — anything else would load in
            # transformers WITHOUT warning and silently diverge
            raise ValueError(
                f"stablelm export requires silu + half rotary + biased "
                f"layernorms and none of moe/post_norms/qk_norm/window/"
                f"softcaps ({cfg.name!r} doesn't fit)"
            )
        if cfg.head_dim != cfg.d_model // cfg.n_heads:
            raise ValueError(
                "stablelm export cannot carry head_dim overrides "
                f"(StableLmConfig has no head_dim field); got "
                f"{cfg.head_dim}"
            )
        out = {
            "model_type": "stablelm",
            "architectures": ["StableLmForCausalLM"],
            "vocab_size": cfg.vocab_size,
            "hidden_size": cfg.d_model,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "num_key_value_heads": cfg.n_kv_heads,
            "intermediate_size": cfg.d_ff,
            "max_position_embeddings": cfg.max_seq_len,
            "rope_theta": cfg.rope_theta,
            "layer_norm_eps": cfg.norm_eps,
            "partial_rotary_factor": cfg.rotary_pct,
            "use_qkv_bias": bool(cfg.qkv_bias if qkv_bias is None else qkv_bias),
            "tie_word_embeddings": cfg.tie_embeddings,
        }
        if cfg.rope_scaling is not None:
            if cfg.rope_scaling[0] != "linear":
                raise ValueError(
                    "stablelm export supports linear rope_scaling only"
                )
            out["rope_scaling"] = {"rope_type": "linear",
                                   "factor": cfg.rope_scaling[1]}
        return out
    if cfg.rotary_pct < 1.0:
        # none of the llama-branch config schemas carry partial rotary —
        # transformers would rotate every head dim and silently diverge
        raise ValueError(
            f"partial rotary (rotary_pct={cfg.rotary_pct}) is not "
            f"representable in the llama-branch export schemas"
        )
    base = {
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.d_model,
        "num_hidden_layers": cfg.n_layers,
        "num_attention_heads": cfg.n_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "intermediate_size": cfg.d_ff,
        "max_position_embeddings": cfg.max_seq_len,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.norm_eps,
        "tie_word_embeddings": cfg.tie_embeddings,
        "head_dim": cfg.head_dim,
    }
    if cfg.sliding_window is not None:
        # EVERY llama-branch family carries the window when set (mixtral
        # and qwen2 too, not just the mistral model_type below) — an
        # export that drops it silently widens attention for HF consumers
        base["sliding_window"] = cfg.sliding_window
    if cfg.rope_scaling is not None:
        if cfg.rope_scaling[0] == "linear":
            base["rope_scaling"] = {"rope_type": "linear",
                                    "factor": cfg.rope_scaling[1]}
        elif cfg.rope_scaling[0] == "yarn":
            _, f, af, bf, bs, orig, trunc = cfg.rope_scaling
            base["rope_scaling"] = {
                # attention_factor written EXPLICITLY: the parse-time
                # inference already folded any mscale variants into it
                "rope_type": "yarn", "factor": f, "attention_factor": af,
                "beta_fast": bf, "beta_slow": bs,
                "original_max_position_embeddings": orig,
                "truncate": trunc,
            }
        else:  # llama3
            _, f, lo, hi, orig = cfg.rope_scaling
            base["rope_scaling"] = {
                "rope_type": "llama3", "factor": f,
                "low_freq_factor": lo, "high_freq_factor": hi,
                "original_max_position_embeddings": orig,
            }
    if cfg.is_moe:
        has_qk = cfg.qk_norm if qk_norm is None else qk_norm
        if has_qk:  # qwen3_moe: qk-norm + per-expert gate/up/down names
            out = {
                "model_type": "qwen3_moe",
                "architectures": ["Qwen3MoeForCausalLM"],
                "num_experts": cfg.n_experts,
                "num_experts_per_tok": cfg.n_experts_per_tok,
                "moe_intermediate_size": cfg.d_ff,
                # our routing renormalizes top-k weights; transformers must
                # too or the mixture weighting silently differs
                "norm_topk_prob": True,
                "decoder_sparse_step": 1,
                "mlp_only_layers": [],
                **base,
            }
            if cfg.sliding_window is not None:
                # Qwen3MoeConfig NULLS sliding_window unless this is set
                out["use_sliding_window"] = True
            return out
        return {
            "model_type": "mixtral",
            "architectures": ["MixtralForCausalLM"],
            "num_local_experts": cfg.n_experts,
            "num_experts_per_tok": cfg.n_experts_per_tok,
            **base,
        }
    if cfg.norm_plus_one:  # gemma family
        act = ("gelu_pytorch_tanh" if cfg.activation == "geglu"
               else cfg.activation)
        has_qk_norm = cfg.qk_norm if qk_norm is None else qk_norm
        if cfg.post_norms and has_qk_norm:  # gemma-3 (text) — keyed on
            # the ACTUAL params like the qwen3 branch, so config.json and
            # the state dict can never describe different families
            if cfg.sliding_window is None or cfg.local_rope_theta is None:
                raise ValueError(
                    "gemma3 export requires sliding_window and "
                    "local_rope_theta (Gemma3TextConfig hardcodes the "
                    "dual-rope local/global structure)"
                )
            out = {
                "model_type": "gemma3_text",
                "architectures": ["Gemma3ForCausalLM"],
                "hidden_activation": act,
                "query_pre_attn_scalar": cfg.attn_scale or cfg.head_dim,
                "rope_local_base_freq": cfg.local_rope_theta,
                # explicit per-layer types: the periodic pattern written
                # out the way transformers stores it
                "layer_types": [
                    ("sliding_attention"
                     if (i % cfg.sliding_window_every)
                     in cfg.sliding_window_residues
                     else "full_attention")
                    for i in range(cfg.n_layers)
                ],
                **base,
            }
            if cfg.attn_logit_softcap:
                out["attn_logit_softcapping"] = cfg.attn_logit_softcap
            if cfg.logits_softcap:
                out["final_logit_softcapping"] = cfg.logits_softcap
            return out
        if cfg.post_norms:  # gemma-2
            if (cfg.sliding_window is None or cfg.sliding_window_every != 2
                    or cfg.sliding_window_residues != (0,)):
                # HF Gemma2 HARDCODES the every-2nd-layer alternation and
                # defaults an omitted sliding_window to 4096 — any other
                # windowing would load in transformers and silently
                # mismatch our per-layer masks
                raise ValueError(
                    "gemma2 export requires sliding_window set with "
                    f"sliding_window_every=2; got window="
                    f"{cfg.sliding_window}, every={cfg.sliding_window_every}"
                )
            return {
                "model_type": "gemma2",
                "architectures": ["Gemma2ForCausalLM"],
                "hidden_act": act,
                "hidden_activation": act,
                "attn_logit_softcapping": cfg.attn_logit_softcap,
                "final_logit_softcapping": cfg.logits_softcap,
                "query_pre_attn_scalar": cfg.attn_scale or cfg.head_dim,
                **base,
            }
        return {
            "model_type": "gemma",
            "architectures": ["GemmaForCausalLM"],
            # transformers >= 4.39 reads hidden_activation and warns on the
            # legacy hidden_act key alone — write both so any version loads
            # the tanh-approx gelu our geglu uses
            "hidden_act": act,
            "hidden_activation": act,
            **base,
        }
    is_qwen3 = cfg.qk_norm if qk_norm is None else qk_norm
    if is_qwen3:  # qwen3: per-head q/k RMSNorm (no qkv biases)
        out = {"model_type": "qwen3", "architectures": ["Qwen3ForCausalLM"],
               **base}
        if cfg.sliding_window is not None:
            out["use_sliding_window"] = True
            out["max_window_layers"] = 0
        return out
    is_qwen2 = cfg.qkv_bias if qkv_bias is None else qkv_bias
    if is_qwen2:
        out = {"model_type": "qwen2", "architectures": ["Qwen2ForCausalLM"], **base}
        if cfg.sliding_window is not None:
            # Qwen2Config defaults use_sliding_window=False, and its
            # max_window_layers default (28) keeps the FIRST 28 layers on
            # full attention — our window applies to every layer, so emit
            # 0 or HF silently ignores the window for <=28-layer models
            out["use_sliding_window"] = True
            out["max_window_layers"] = 0
        return out
    if cfg.sliding_window is not None:  # mistral family (zephyr-7b etc.):
        # exporting as plain llama would silently widen the attention
        # window for any consumer that respects config.json
        return {
            "model_type": "mistral",
            "architectures": ["MistralForCausalLM"],
            **base,
        }
    return {"model_type": "llama", "architectures": ["LlamaForCausalLM"], **base}


def export_hf(params, cfg: ModelConfig, out_dir: str | Path,
              dtype: str = "float32") -> Path:
    """Write ``out_dir/model.safetensors`` + ``config.json`` in the HF layout
    for this config's family. Round-trips through models/loader, and loads
    in torch/transformers via ``from_pretrained(out_dir)``."""
    from . import core

    params = core.restack_layers(params)  # no-op unless a CPU engine's
    # unstacked list — the exporters index stacked [L, ...] arrays
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)
    # key the family choice on the ACTUAL params: a bias-carrying tree
    # under a biasless config must still export as qwen2 (see hf_config_dict)
    has_qkv_bias = (
        None if cfg.pos_embedding == "learned"
        else "bq" in params["layers"].get("attn", {})
    )
    # qwen3 keyed on the ACTUAL params too: config.json and the state
    # dict must describe the same family, or from_pretrained silently
    # random-inits (or drops) the q/k norm tensors
    has_qk_norm = (
        None if cfg.pos_embedding == "learned"
        else "q_norm" in params["layers"].get("attn", {})
    )
    # validate the config BEFORE building the state dict: unsupported
    # combos must die with hf_config_dict's explanation, not a KeyError
    # halfway through a tensor conversion
    cfg_json = hf_config_dict(cfg, qkv_bias=has_qkv_bias, qk_norm=has_qk_norm)
    np_dtype = np.dtype(dtype) if dtype != "bfloat16" else _bf16_dtype()
    if cfg.pos_embedding == "alibi" and not cfg.use_bias:  # mpt
        state = _export_mpt_state(params, cfg, np_dtype)
    elif cfg.pos_embedding == "alibi":
        state = _export_bloom_state(params, cfg, np_dtype)
    elif cfg.pos_embedding == "learned" and cfg.n_kv_heads != cfg.n_heads:
        state = _export_bigcode_state(params, cfg, np_dtype)
    elif cfg.pos_embedding == "learned":
        state = _export_gpt2_state(params, cfg, np_dtype)
    elif cfg.parallel_block and cfg.rope_style == "interleaved":
        # SAME ordering as hf_config_dict: the two dispatch chains must
        # classify a config identically or the config.json and tensor
        # names would describe different families
        state = _export_gptj_state(params, cfg, np_dtype)
    elif cfg.parallel_block and cfg.parallel_norms == 2:
        state = _export_neox_state(params, cfg, np_dtype)
    elif cfg.parallel_block and not cfg.use_bias:  # falcon — same position
        # in the chain as hf_config_dict's classification
        state = _export_falcon_state(params, cfg, np_dtype)
    elif cfg.parallel_block:
        state = _export_phi_state(params, cfg, np_dtype)
    else:
        state = _export_llama_state(params, cfg, np_dtype)
    write_safetensors(
        out / "model.safetensors", state,
        metadata={"format": "pt", "exported_by": "bee2bee_tpu"},
    )
    (out / "config.json").write_text(json.dumps(cfg_json, indent=2))
    return out


def _bf16_dtype():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)
