"""Model configurations: one dataclass drives the shared transformer core.

Preset registry covers the BASELINE.md measurement ladder (distilgpt2,
gemma-2b, llama-3-8b, zephyr-7b, mixtral-8x7b) plus tiny variants for tests.
HF checkpoint names map onto these presets by fuzzy match, mirroring the
reference's model-tag matching (reference services.py:136-151).
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, fields, replace
from pathlib import Path

logger = logging.getLogger("bee2bee_tpu.models.config")


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    max_seq_len: int = 2048
    # architecture switches
    pos_embedding: str = "rope"  # "rope" | "learned" | "alibi" (bloom:
    # linear attention-score bias per head, no embedding-side positions)
    norm: str = "rmsnorm"  # "rmsnorm" | "layernorm"
    norm_bias: bool = True  # layernorm only: mpt ships weight-only norms
    activation: str = "silu"  # "silu" (gated) | "gelu" (tanh approx, gpt2/
    # phi) | "gelu_exact" (erf — gpt-neox) | "geglu"
    use_bias: bool = False  # attn/mlp biases (gpt2 style)
    qkv_bias: bool = False  # bias on q/k/v ONLY (qwen2 style; no bo/mlp bias)
    qk_norm: bool = False  # per-head RMSNorm on q and k before rope
    # (qwen3 style; learned [head_dim] scales)
    qk_norm_full: bool = False  # with qk_norm: normalize the WHOLE q/k
    # projection width instead of per head (olmo2: [H*hd]/[Hkv*hd] scales)
    tie_embeddings: bool = True
    rope_theta: float = 10000.0
    # frequency-domain RoPE scaling, encoded as a hashable tuple:
    #   ("linear", factor)  — position-interpolation fine-tunes
    #   ("llama3", factor, low_freq_factor, high_freq_factor,
    #    original_max_position_embeddings)  — llama-3.1+ checkpoints
    #   ("yarn", factor, attention_factor, beta_fast, beta_slow,
    #    original_max_position_embeddings, truncate)  — NTK-by-parts
    #    long-context fine-tunes (attention_factor resolved at parse time,
    #    incl. the deepseek mscale variants)
    rope_scaling: tuple | None = None
    norm_eps: float = 1e-5
    logits_softcap: float | None = None
    embedding_scale: bool = False  # gemma multiplies embeds by sqrt(d_model)
    norm_plus_one: bool = False  # gemma checkpoints store rmsnorm as (1 + w)
    # phi/gpt-neox-style switches
    rotary_pct: float = 1.0  # fraction of head_dim that rotates (phi-2: 0.4)
    rope_style: str = "half"  # "half": rotate (first, second) halves of the
    # rotary dims as a block (llama/neox/phi); "interleaved": rotate
    # adjacent pairs (x[2i], x[2i+1]) — gpt-j's rotate_every_two
    mlp_bias: bool = False  # biases on the MLP matmuls ONLY (gpt-j: fc_in/
    # fc_out carry biases while the attention projections have none)
    lm_head_bias: bool = False  # untied lm_head carries a bias (phi)
    # sliding-window attention (mistral): each query attends to at most
    # the last `sliding_window` positions. None = full causal. Supported
    # by the dense attention path (engine validates flash/sp against it).
    sliding_window: int | None = None
    # with sliding_window set: layers whose layer_idx % sliding_window_every
    # falls in sliding_window_residues window, the rest attend fully.
    # 1 = every layer (mistral); every=2/residues=(0,) = gemma-2's
    # alternation; every=6/residues=(0,1,2,3,4) = gemma-3's 5-local-1-global
    sliding_window_every: int = 1
    sliding_window_residues: tuple = (0,)
    # gemma-3: SLIDING layers rotate with this theta and NO rope_scaling;
    # global layers use rope_theta + rope_scaling. None = one rope for all
    local_rope_theta: float | None = None
    # gemma-2 attention extras
    attn_logit_softcap: float | None = None  # tanh cap on attention scores
    attn_scale: float | None = None  # score denominator becomes
    # sqrt(attn_scale) instead of sqrt(head_dim) (query_pre_attn_scalar)
    post_norms: bool = False  # gemma-2: extra norms on the attn and mlp
    # OUTPUTS before they join the residual (4 norms per block)
    no_pre_norms: bool = False  # olmo2: NO ln1/ln2 pre-norms — the
    # post-output norms (post_norms must be set) are the only block norms
    parallel_block: bool = False  # x + attn(ln(x)) + mlp(ln'(x)) parallel
    # residual (phi/gpt-neox); sequential pre-norm blocks otherwise
    parallel_norms: int = 1  # parallel blocks only: 1 = attn and mlp share
    # ln1 (phi); 2 = mlp gets its own ln2 (gpt-neox use_parallel_residual)
    # MoE
    n_experts: int = 0  # 0 = dense
    n_experts_per_tok: int = 2
    # "dense": all experts on all tokens, weight-masked — the exact
    # reference formulation (correctness baseline, 4x routed FLOPs at
    # top-2-of-8). "routed": GShard-style capacity-grouped dispatch; only
    # routed tokens hit each expert, tokens past capacity drop.
    moe_impl: str = "dense"  # "dense" | "routed"
    moe_capacity_factor: float = 1.25  # routed: C = ceil(g*k/E * factor)
    # routed dispatch runs per GROUP of this many tokens (GShard grouping):
    # capacity — and so the [*, g, E, C] dispatch tensor — stays O(group
    # size), not O(batch*seq). Groups route independently.
    moe_group_size: int = 512

    # bloom: LayerNorm over the embeddings before block 0
    embedding_norm: bool = False

    def __post_init__(self):
        if self.sliding_window_residues != (0,):
            object.__setattr__(self, "sliding_window_residues",
                               tuple(self.sliding_window_residues))
        if self.rope_scaling is not None:
            # normalize a json list back to the hashable tuple form (the
            # native-checkpoint model_config.json round-trip)
            object.__setattr__(self, "rope_scaling", tuple(self.rope_scaling))
            kind = self.rope_scaling[0]
            want = {"linear": 2, "llama3": 5, "yarn": 7}.get(kind)
            if want is None or len(self.rope_scaling) != want:
                raise ValueError(
                    f"rope_scaling={self.rope_scaling!r}: expected "
                    f"('linear', factor), ('llama3', factor, low_freq, "
                    f"high_freq, original_max_pos), or ('yarn', factor, "
                    f"attention_factor, beta_fast, beta_slow, "
                    f"original_max_pos, truncate)"
                )
        if self.no_pre_norms and not self.post_norms:
            raise ValueError(
                "no_pre_norms requires post_norms — the block would have "
                "ZERO normalization otherwise (olmo2 sets both)"
            )
        if self.pos_embedding not in ("rope", "learned", "alibi"):
            raise ValueError(
                f"pos_embedding={self.pos_embedding!r} must be 'rope', "
                f"'learned', or 'alibi'"
            )
        if self.rope_style not in ("half", "interleaved"):
            # a typo here would silently rotate the wrong way (core._rope
            # has no else-error) — fail like moe_impl does
            raise ValueError(
                f"rope_style={self.rope_style!r} must be 'half' or 'interleaved'"
            )
        if self.moe_impl not in ("dense", "routed"):
            raise ValueError(
                f"moe_impl={self.moe_impl!r} must be 'dense' or 'routed'"
            )
        if self.moe_group_size < 1:
            raise ValueError(f"moe_group_size={self.moe_group_size} must be >= 1")

    # families where attention width != d_model (gemma-7b: 16 heads of 256
    # over d_model 3072) set this; None derives d_model // n_heads
    head_dim_override: int | None = None

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.d_model // self.n_heads

    @property
    def rotary_dim(self) -> int:
        """Head dims that actually rotate: floor-to-even rotary_pct *
        head_dim (HF's int() truncation) — THE one formula core._rope and
        the exporters share."""
        if self.rotary_pct >= 1.0:
            return self.head_dim
        return max(2, int(self.head_dim * self.rotary_pct) // 2 * 2)

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0


def _gpt2(name, d_model, n_layers, n_heads, d_ff=None, vocab=50257, max_pos=1024):
    return ModelConfig(
        name=name,
        vocab_size=vocab,
        d_model=d_model,
        n_layers=n_layers,
        n_heads=n_heads,
        n_kv_heads=n_heads,
        d_ff=d_ff or 4 * d_model,
        max_seq_len=max_pos,
        pos_embedding="learned",
        norm="layernorm",
        activation="gelu",
        use_bias=True,
        tie_embeddings=True,
    )


CONFIGS: dict[str, ModelConfig] = {
    # -- test-sized --
    "tiny-gpt2": _gpt2("tiny-gpt2", d_model=64, n_layers=2, n_heads=4, vocab=512, max_pos=256),
    "tiny-llama": ModelConfig(
        name="tiny-llama", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=256,
    ),
    "tiny-llama-4l": ModelConfig(  # 4 layers: pipeline splits deeper than
        # 2 stages (layer_ranges caps n_stages at n_layers) — the
        # pipeline_interleave bench/test topology at 4 stages
        name="tiny-llama-4l", vocab_size=512, d_model=64, n_layers=4,
        n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=256,
    ),
    "tiny-mixtral": ModelConfig(
        name="tiny-mixtral", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=256, n_experts=4, n_experts_per_tok=2,
    ),
    "tiny-gemma": ModelConfig(  # MQA (one kv head): the KV-replication path
        name="tiny-gemma", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=1, d_ff=128, max_seq_len=256, activation="geglu",
        embedding_scale=True, norm_plus_one=True, norm_eps=1e-6,
    ),
    "tiny-qwen3": ModelConfig(  # llama arch + per-head q/k RMSNorm
        name="tiny-qwen3", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=256, qk_norm=True,
        rope_theta=1000000.0, norm_eps=1e-6, tie_embeddings=False,
    ),
    "tiny-mistral": ModelConfig(  # llama arch + sliding-window attention,
        # window deliberately smaller than the test prompts so the windowed
        # mask is actually exercised against HF's implementation
        name="tiny-mistral", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=256, sliding_window=4,
    ),
    "tiny-gemma2": ModelConfig(  # gemma-2: post-norms, attn softcap,
        # query scale override, ALTERNATING local/global attention
        # (window 4 < the 8-token test prompts, every 2nd layer)
        name="tiny-gemma2", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=256,
        activation="geglu", embedding_scale=True, norm_plus_one=True,
        norm_eps=1e-6, post_norms=True, attn_logit_softcap=50.0,
        logits_softcap=30.0, attn_scale=32.0, sliding_window=4,
        sliding_window_every=2,
    ),
    "tiny-gemma3": ModelConfig(  # gemma-3: gemma-2 post-norms + (1+w)
        # per-head qk-norm + DUAL rope (local 10k on sliding layers,
        # global theta + linear scaling on the rest) + 2-local-1-global
        # pattern (period 3 keeps a 3-layer tiny model exercising both)
        name="tiny-gemma3", vocab_size=512, d_model=64, n_layers=3,
        n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=256,
        activation="geglu", embedding_scale=True, norm_plus_one=True,
        norm_eps=1e-6, post_norms=True, qk_norm=True, attn_scale=32.0,
        rope_theta=1000000.0, local_rope_theta=10000.0,
        rope_scaling=("linear", 8.0), sliding_window=4,
        sliding_window_every=3, sliding_window_residues=(0, 1),
    ),
    "tiny-qwen": ModelConfig(  # qwen2 style: llama arch + q/k/v-only bias
        name="tiny-qwen", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
        n_kv_heads=2, d_ff=128, max_seq_len=256, qkv_bias=True,
        rope_theta=1000000.0,
    ),
    # gpt-bigcode / starcoder style: gpt2 block + MQA, tanh gelu
    "tiny-bigcode": ModelConfig(
        name="tiny-bigcode", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=1, d_ff=128, max_seq_len=256,
        pos_embedding="learned", norm="layernorm", activation="gelu",
        use_bias=True, tie_embeddings=True,
    ),
    "starcoder-15b": ModelConfig(
        # bigcode/starcoderbase: 48 128-dim heads with ONE kv head over a
        # gpt2-style learned-position block, 8k context
        name="starcoder-15b", vocab_size=49152, d_model=6144, n_layers=40,
        n_heads=48, n_kv_heads=1, d_ff=24576, max_seq_len=8192,
        pos_embedding="learned", norm="layernorm", activation="gelu",
        use_bias=True, tie_embeddings=True,
    ),
    # -- BASELINE ladder --
    "distilgpt2": _gpt2("distilgpt2", d_model=768, n_layers=6, n_heads=12),
    "gpt2": _gpt2("gpt2", d_model=768, n_layers=12, n_heads=12),
    "gemma-2b": ModelConfig(
        # head_dim = 2048/8 = 256, matching gemma's 256-dim heads
        name="gemma-2b", vocab_size=256000, d_model=2048, n_layers=18, n_heads=8,
        n_kv_heads=1, d_ff=16384, max_seq_len=8192, activation="geglu",
        embedding_scale=True, norm_eps=1e-6, norm_plus_one=True,
    ),
    "llama-3-8b": ModelConfig(
        name="llama-3-8b", vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, max_seq_len=8192, rope_theta=500000.0,
        tie_embeddings=False,
    ),
    "zephyr-7b": ModelConfig(  # mistral-7b architecture (HuggingFaceH4/zephyr-7b-beta)
        name="zephyr-7b", vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
        sliding_window=4096,
        n_kv_heads=8, d_ff=14336, max_seq_len=4096, tie_embeddings=False,
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b", vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
        n_kv_heads=8, d_ff=14336, max_seq_len=8192, tie_embeddings=False,
        n_experts=8, n_experts_per_tok=2,
    ),
    # -- qwen2 family (llama arch + q/k/v bias, 1e6 rope theta) --
    "qwen2-0.5b": ModelConfig(
        name="qwen2-0.5b", vocab_size=151936, d_model=896, n_layers=24,
        n_heads=14, n_kv_heads=2, d_ff=4864, max_seq_len=32768,
        qkv_bias=True, rope_theta=1000000.0, norm_eps=1e-6,
    ),
    "qwen2-7b": ModelConfig(
        name="qwen2-7b", vocab_size=152064, d_model=3584, n_layers=28,
        n_heads=28, n_kv_heads=4, d_ff=18944, max_seq_len=32768,
        qkv_bias=True, rope_theta=1000000.0, norm_eps=1e-6,
        tie_embeddings=False,
    ),
    # -- qwen3 family (llama arch + per-head q/k RMSNorm, no qkv bias) --
    "qwen3-8b": ModelConfig(
        name="qwen3-8b", vocab_size=151936, d_model=4096, n_layers=36,
        n_heads=32, n_kv_heads=8, d_ff=12288, max_seq_len=40960,
        qk_norm=True, rope_theta=1000000.0, norm_eps=1e-6,
        tie_embeddings=False,
    ),
    "tiny-qwen3moe": ModelConfig(  # qwen3 qk-norm + qwen3_moe expert names
        name="tiny-qwen3moe", vocab_size=512, d_model=64, n_layers=2,
        n_heads=4, n_kv_heads=2, d_ff=32, max_seq_len=256, qk_norm=True,
        rope_theta=1000000.0, norm_eps=1e-6, tie_embeddings=False,
        n_experts=4, n_experts_per_tok=2,
    ),
    "qwen3-30b-a3b": ModelConfig(
        # Qwen/Qwen3-30B-A3B: 128 experts, 8 active, 768-wide experts,
        # per-head qk-norm, head_dim 128 over d_model 2048
        name="qwen3-30b-a3b", vocab_size=151936, d_model=2048, n_layers=48,
        n_heads=32, n_kv_heads=4, d_ff=768, max_seq_len=40960,
        qk_norm=True, rope_theta=1000000.0, norm_eps=1e-6,
        tie_embeddings=False, head_dim_override=128,
        n_experts=128, n_experts_per_tok=8,
    ),
    # -- larger members of the already-supported families --
    "gemma-2-9b": ModelConfig(
        # google/gemma-2-9b: 16 256-dim heads over d_model 3584 (override),
        # alternating 4096-window/global layers, softcapped scores+logits
        name="gemma-2-9b", vocab_size=256000, d_model=3584, n_layers=42,
        n_heads=16, n_kv_heads=8, d_ff=14336, max_seq_len=8192,
        activation="geglu", embedding_scale=True, norm_plus_one=True,
        norm_eps=1e-6, head_dim_override=256, post_norms=True,
        attn_logit_softcap=50.0, logits_softcap=30.0, attn_scale=256.0,
        sliding_window=4096, sliding_window_every=2,
    ),
    "gemma-3-4b": ModelConfig(
        # google/gemma-3-4b (text config): 8 256-dim heads over d_model
        # 2304, 5-local-1-global 1024-token windows, dual rope (local 10k;
        # global 1M with linear-8 scaling), 128k context
        name="gemma-3-4b", vocab_size=262208, d_model=2304, n_layers=34,
        n_heads=8, n_kv_heads=4, d_ff=9216, max_seq_len=131072,
        activation="geglu", embedding_scale=True, norm_plus_one=True,
        norm_eps=1e-6, head_dim_override=256, post_norms=True,
        qk_norm=True, attn_scale=256.0, rope_theta=1000000.0,
        local_rope_theta=10000.0, rope_scaling=("linear", 8.0),
        sliding_window=1024, sliding_window_every=6,
        sliding_window_residues=(0, 1, 2, 3, 4),
    ),
    "gemma-7b": ModelConfig(
        # attention width 4096 != d_model 3072: heads are 256-dim like
        # gemma-2b's, hence the explicit head_dim_override
        name="gemma-7b", vocab_size=256000, d_model=3072, n_layers=28, n_heads=16,
        n_kv_heads=16, d_ff=24576, max_seq_len=8192, activation="geglu",
        embedding_scale=True, norm_eps=1e-6, norm_plus_one=True,
        head_dim_override=256,
    ),
    "llama-3-70b": ModelConfig(
        name="llama-3-70b", vocab_size=128256, d_model=8192, n_layers=80,
        n_heads=64, n_kv_heads=8, d_ff=28672, max_seq_len=8192,
        rope_theta=500000.0, tie_embeddings=False,
    ),
}

# zephyr IS mistral-7b architecture — one definition, two names (drift-proof)
CONFIGS["mistral-7b"] = replace(CONFIGS["zephyr-7b"], name="mistral-7b")
# llama-3.1: same weights-shape as llama-3 + the llama3 rope-scaling
# schedule over a 128k window (config.json: rope_scaling.rope_type=llama3)
CONFIGS["llama-3.1-8b"] = replace(
    CONFIGS["llama-3-8b"], name="llama-3.1-8b", max_seq_len=131072,
    rope_scaling=("llama3", 8.0, 1.0, 4.0, 8192),
)

CONFIGS["tiny-phi"] = ModelConfig(  # parallel blocks + partial rotary
    name="tiny-phi", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
    n_kv_heads=4, d_ff=128, max_seq_len=256, activation="gelu",
    norm="layernorm", use_bias=True, tie_embeddings=False,
    rotary_pct=0.4, parallel_block=True, lm_head_bias=True,
)
CONFIGS["tiny-gptj"] = ModelConfig(  # interleaved rotary + mlp-only bias
    name="tiny-gptj", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
    n_kv_heads=4, d_ff=128, max_seq_len=256, activation="gelu",
    norm="layernorm", tie_embeddings=False, mlp_bias=True,
    rotary_pct=0.5, rope_style="interleaved", parallel_block=True,
    lm_head_bias=True,
)
CONFIGS["gpt-j-6b"] = ModelConfig(
    # EleutherAI/gpt-j-6b: parallel block sharing one layernorm,
    # interleaved rotary over 64 of 256 head dims, bias-free attention
    # with biased MLP and lm_head
    name="gpt-j-6b", vocab_size=50400, d_model=4096, n_layers=28,
    n_heads=16, n_kv_heads=16, d_ff=16384, max_seq_len=2048,
    activation="gelu", norm="layernorm", tie_embeddings=False,
    mlp_bias=True, rotary_pct=0.25, rope_style="interleaved",
    parallel_block=True, lm_head_bias=True,
)
CONFIGS["tiny-bloom"] = ModelConfig(  # ALiBi attention (no rotary/learned
    # positions), embedding LayerNorm before block 0, biased everything
    name="tiny-bloom", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
    n_kv_heads=4, d_ff=256, max_seq_len=256, pos_embedding="alibi",
    norm="layernorm", activation="gelu", use_bias=True,
    tie_embeddings=True, embedding_norm=True,
)
CONFIGS["bloom-7b1"] = ModelConfig(
    # bigscience/bloom-7b1: 30 layers x 32 heads, ALiBi, 250k vocab
    name="bloom-7b1", vocab_size=250880, d_model=4096, n_layers=30,
    n_heads=32, n_kv_heads=32, d_ff=16384, max_seq_len=2048,
    pos_embedding="alibi", norm="layernorm", activation="gelu",
    use_bias=True, tie_embeddings=True, embedding_norm=True,
)
CONFIGS["tiny-mpt"] = ModelConfig(  # mpt style: ALiBi + weight-only
    # layernorms + zero linear biases + exact gelu, sequential blocks
    name="tiny-mpt", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
    n_kv_heads=4, d_ff=256, max_seq_len=256, pos_embedding="alibi",
    norm="layernorm", norm_bias=False, activation="gelu_exact",
    tie_embeddings=True,
)
CONFIGS["mpt-7b"] = ModelConfig(
    # mosaicml/mpt-7b: 32 heads (power of two — the bloom slope formula
    # applies exactly), expansion ratio 4, no biases anywhere
    name="mpt-7b", vocab_size=50432, d_model=4096, n_layers=32,
    n_heads=32, n_kv_heads=32, d_ff=16384, max_seq_len=2048,
    pos_embedding="alibi", norm="layernorm", norm_bias=False,
    activation="gelu_exact", tie_embeddings=True,
)
CONFIGS["tiny-falcon"] = ModelConfig(  # falcon-7b shape: MQA + bias-free
    # parallel block sharing ONE layernorm, exact-erf gelu, tied head
    name="tiny-falcon", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
    n_kv_heads=1, d_ff=128, max_seq_len=256, activation="gelu_exact",
    norm="layernorm", tie_embeddings=True, parallel_block=True,
)
CONFIGS["falcon-7b"] = ModelConfig(
    # tiiuae/falcon-7b: 71 64-dim heads with ONE kv head (multi_query),
    # parallel attn+mlp sharing input_layernorm, no linear biases, tied
    # embeddings, full rotary
    name="falcon-7b", vocab_size=65024, d_model=4544, n_layers=32,
    n_heads=71, n_kv_heads=1, d_ff=18176, max_seq_len=2048,
    activation="gelu_exact", norm="layernorm", tie_embeddings=True,
    parallel_block=True,
)
CONFIGS["tiny-neox"] = ModelConfig(  # dual-norm parallel residual
    name="tiny-neox", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
    n_kv_heads=4, d_ff=128, max_seq_len=256, activation="gelu_exact",
    norm="layernorm", use_bias=True, tie_embeddings=False,
    rotary_pct=0.25, parallel_block=True, parallel_norms=2,
)
CONFIGS["pythia-1.4b"] = ModelConfig(
    # EleutherAI/pythia-1.4b (GPT-NeoX arch): parallel residual with
    # separate attn/mlp norms, rotary over the first quarter of head dims
    name="pythia-1.4b", vocab_size=50304, d_model=2048, n_layers=24,
    n_heads=16, n_kv_heads=16, d_ff=8192, max_seq_len=2048,
    activation="gelu_exact", norm="layernorm", use_bias=True,
    tie_embeddings=False, rotary_pct=0.25, parallel_block=True,
    parallel_norms=2,
)
CONFIGS["gpt-neox-20b"] = ModelConfig(
    name="gpt-neox-20b", vocab_size=50432, d_model=6144, n_layers=44,
    n_heads=64, n_kv_heads=64, d_ff=24576, max_seq_len=2048,
    activation="gelu_exact", norm="layernorm", use_bias=True,
    tie_embeddings=False, rotary_pct=0.25, parallel_block=True,
    parallel_norms=2,
)
CONFIGS["tiny-olmo2"] = ModelConfig(
    # olmo2 style: POST-norm-only blocks + full-width q/k RMSNorm
    name="tiny-olmo2", vocab_size=512, d_model=64, n_layers=2, n_heads=4,
    n_kv_heads=2, d_ff=128, max_seq_len=256, tie_embeddings=False,
    post_norms=True, no_pre_norms=True, qk_norm=True, qk_norm_full=True,
)
CONFIGS["olmo2-7b"] = ModelConfig(
    # allenai/OLMo-2-1124-7B: fully-open 7B, rope theta 5e5, 100k vocab
    name="olmo2-7b", vocab_size=100352, d_model=4096, n_layers=32,
    n_heads=32, n_kv_heads=32, d_ff=11008, max_seq_len=4096,
    rope_theta=500000.0, norm_eps=1e-6, tie_embeddings=False,
    post_norms=True, no_pre_norms=True, qk_norm=True, qk_norm_full=True,
)
CONFIGS["tiny-stablelm"] = ModelConfig(
    # stablelm-2 style: llama tensor layout with BIASED layernorms,
    # partial rotary 0.25, gated silu, untied head
    name="tiny-stablelm", vocab_size=512, d_model=64, n_layers=2,
    n_heads=4, n_kv_heads=2, d_ff=128, max_seq_len=256, norm="layernorm",
    rotary_pct=0.25, tie_embeddings=False,
)
CONFIGS["stablelm-2-1.6b"] = ModelConfig(
    # stabilityai/stablelm-2-1_6b ships use_qkv_bias=true (the qwen-style
    # per-projection q/k/v biases are a defining stablelm-2 feature)
    name="stablelm-2-1.6b", vocab_size=100352, d_model=2048, n_layers=24,
    n_heads=32, n_kv_heads=32, d_ff=5632, max_seq_len=4096,
    norm="layernorm", rotary_pct=0.25, qkv_bias=True, tie_embeddings=False,
)
CONFIGS["phi-3-mini"] = ModelConfig(
    # microsoft/Phi-3-mini-4k-instruct: llama-branch arch behind fused
    # qkv_proj/gate_up_proj tensors (loader._convert_phi3 un-fuses),
    # 2047-token sliding window on every layer. The 128k variants use
    # longrope scaling, which config_from_hf refuses (unimplemented).
    name="phi-3-mini", vocab_size=32064, d_model=3072, n_layers=32,
    n_heads=32, n_kv_heads=32, d_ff=8192, max_seq_len=4096,
    tie_embeddings=False, sliding_window=2047,
)
CONFIGS["phi-2"] = ModelConfig(
    # microsoft/phi-2: 2.7B, parallel attn+mlp blocks sharing one
    # layernorm, partial rotary over the first 32 of 80 head dims,
    # untied lm_head with bias
    name="phi-2", vocab_size=51200, d_model=2560, n_layers=32, n_heads=32,
    n_kv_heads=32, d_ff=10240, max_seq_len=2048, activation="gelu",
    norm="layernorm", use_bias=True, tie_embeddings=False,
    rotary_pct=0.4, parallel_block=True, lm_head_bias=True,
)


def _neox_act(hidden_act: str) -> str:
    if hidden_act in ("gelu_new", "gelu_pytorch_tanh", "gelu_fast"):
        return "gelu"
    if hidden_act == "gelu":
        return "gelu_exact"
    raise ValueError(
        f"gpt_neox hidden_act {hidden_act!r} is not supported by the native "
        f"core (gelu variants only)"
    )


def _parse_rope_scaling(d: dict, default_max_pos: int = 2048) -> tuple | None:
    """HF rope_scaling dict → cfg.rope_scaling tuple, or raise for
    schedules the core doesn't implement (yarn/longrope/dynamic) — every
    rotary family must route through this, or an extended-context
    fine-tune serves with unscaled rotations, silently wrong at every
    position."""
    rs = d.get("rope_scaling")
    if not rs:
        return None
    rtype = rs.get("rope_type") or rs.get("type")
    if rtype == "llama3":
        return ("llama3", float(rs["factor"]), float(rs["low_freq_factor"]),
                float(rs["high_freq_factor"]),
                int(rs["original_max_position_embeddings"]))
    if rtype == "linear":
        return ("linear", float(rs["factor"]))
    if rtype == "yarn":
        import math as _math

        factor = float(rs["factor"])
        af = rs.get("attention_factor")
        if af is None:
            # HF's inference rule, incl. the deepseek mscale variants
            def get_mscale(scale, ms=1.0):
                return 1.0 if scale <= 1 else 0.1 * ms * _math.log(scale) + 1.0

            ms, msad = rs.get("mscale"), rs.get("mscale_all_dim")
            af = (get_mscale(factor, ms) / get_mscale(factor, msad)
                  if ms and msad else get_mscale(factor))
        orig = (rs.get("original_max_position_embeddings")
                or d.get("max_position_embeddings", default_max_pos))
        return ("yarn", factor, float(af),
                float(rs.get("beta_fast") or 32),
                float(rs.get("beta_slow") or 1),
                int(orig), bool(rs.get("truncate", True)))
    if rtype in ("default", None):
        return None
    raise ValueError(
        f"rope_scaling type {rtype!r} is not supported by the native core "
        f"(llama3/linear/yarn only); serve via the ollama/remote backends"
    )


def config_from_hf(d: dict, name: str | None = None) -> ModelConfig:
    """Synthesize a ModelConfig from an HF ``config.json`` dict — the
    any-checkpoint path: a checkpoint whose architecture is NOT in the
    preset registry can still be served natively, the way the reference
    serves any HF causal LM via AutoModelForCausalLM (reference
    services.py:39-52, hf.py:23-32). Inverse of export.hf_config_dict;
    covers the gpt2 / llama / mistral / qwen2 / gemma / mixtral / phi /
    gpt-neox / gpt-j layouts (the dominant open-model shapes)."""
    mt = d.get("model_type")
    nm = name or d.get("_name_or_path") or f"{mt}-checkpoint"
    if mt == "gpt2":
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["n_embd"],
            n_layers=d["n_layer"], n_heads=d["n_head"], n_kv_heads=d["n_head"],
            d_ff=d.get("n_inner") or 4 * d["n_embd"],
            max_seq_len=d.get("n_positions", 1024), pos_embedding="learned",
            norm="layernorm", activation="gelu", use_bias=True,
            tie_embeddings=True,
            norm_eps=d.get("layer_norm_epsilon", 1e-5),
        )
    if mt == "gpt_bigcode":
        H = d["n_head"]
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["n_embd"],
            n_layers=d["n_layer"], n_heads=H,
            n_kv_heads=1 if d.get("multi_query", True) else H,
            d_ff=d.get("n_inner") or 4 * d["n_embd"],
            max_seq_len=d.get("n_positions", 1024), pos_embedding="learned",
            norm="layernorm",
            # same gelu-dialect map (and refusal of non-gelu) as gpt_neox:
            # an exact-gelu checkpoint must not silently run tanh-approx
            activation=_neox_act(d.get("activation_function",
                                       "gelu_pytorch_tanh")),
            use_bias=True,
            tie_embeddings=d.get("tie_word_embeddings", True),
            norm_eps=d.get("layer_norm_epsilon", 1e-5),
        )
    if mt == "gptj":
        hd = d["n_embd"] // d["n_head"]
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["n_embd"],
            n_layers=d["n_layer"], n_heads=d["n_head"], n_kv_heads=d["n_head"],
            d_ff=d.get("n_inner") or 4 * d["n_embd"],
            max_seq_len=d.get("n_positions", 2048), activation="gelu",
            norm="layernorm", tie_embeddings=False, mlp_bias=True,
            rotary_pct=d.get("rotary_dim", hd) / hd, rope_style="interleaved",
            parallel_block=True, lm_head_bias=True,
            norm_eps=d.get("layer_norm_epsilon", 1e-5),
        )
    if mt == "gpt_neox":
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=d["num_hidden_layers"], n_heads=d["num_attention_heads"],
            n_kv_heads=d["num_attention_heads"], d_ff=d["intermediate_size"],
            max_seq_len=d.get("max_position_embeddings", 2048),
            # HF "gelu" is the exact erf form; the tanh approximations are
            # spelled gelu_new / gelu_pytorch_tanh. Anything else must
            # fail loudly — a silently substituted nonlinearity serves
            # garbage with no error
            activation=_neox_act(d.get("hidden_act", "gelu")),
            norm="layernorm", use_bias=True,
            tie_embeddings=d.get("tie_word_embeddings", False),
            rotary_pct=d.get("rotary_pct", 1.0),
            rope_theta=d.get("rotary_emb_base", 10000.0),
            rope_scaling=_parse_rope_scaling(d),
            parallel_block=d.get("use_parallel_residual", True),
            parallel_norms=2, norm_eps=d.get("layer_norm_eps", 1e-5),
        )
    if mt == "mpt":
        ac = d.get("attn_config") or {}
        if not ac.get("alibi", True):
            raise ValueError(
                "mpt without alibi (learned-pos variant) is not supported "
                "by the native core; serve via the ollama/remote backends"
            )
        if ac.get("clip_qkv") or ac.get("softmax_scale"):
            raise ValueError(
                "mpt clip_qkv / custom softmax_scale are not supported by "
                "the native core"
            )
        H = d["n_heads"]
        if H & (H - 1):
            # MPT's non-power-of-two slope interleave differs from the
            # bloom formula core.alibi_slopes implements — refuse rather
            # than attend with wrong biases
            raise ValueError(
                f"mpt with non-power-of-two n_heads={H} is not supported "
                f"(ALiBi slope schedule differs)"
            )
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["d_model"],
            n_layers=d["n_layers"], n_heads=H, n_kv_heads=H,
            d_ff=int(d.get("expansion_ratio", 4)) * d["d_model"],
            max_seq_len=d.get("max_seq_len", 2048), pos_embedding="alibi",
            norm="layernorm", norm_bias=False, activation="gelu_exact",
            tie_embeddings=d.get("tie_word_embeddings", True),
            norm_eps=d.get("layer_norm_epsilon", 1e-5),
        )
    if mt == "bloom":
        if d.get("apply_residual_connection_post_layernorm"):
            # HF adds the post-LN hidden states to the residual under this
            # flag; our blocks always use the pre-LN input — serving such
            # a checkpoint would diverge at every layer, silently
            raise ValueError(
                "bloom apply_residual_connection_post_layernorm=true is "
                "not supported by the native core; serve via the "
                "ollama/remote backends"
            )
        H = d["n_head"]
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=d["n_layer"], n_heads=H, n_kv_heads=H,
            d_ff=4 * d["hidden_size"],  # BloomConfig has no n_inner field
            # ALiBi has no positional table — context is bounded only by
            # the serving cache; seq_length is the training length the
            # wild checkpoints carry (2048 for the bloom releases)
            max_seq_len=d.get("seq_length", 2048),
            pos_embedding="alibi", norm="layernorm",
            activation="gelu", use_bias=True,
            tie_embeddings=d.get("tie_word_embeddings", True),
            embedding_norm=True, norm_eps=d.get("layer_norm_epsilon", 1e-5),
        )
    if mt == "falcon":
        if d.get("alibi"):
            raise ValueError(
                "falcon alibi checkpoints are not supported by the native "
                "core (rotary only); serve via the ollama/remote backends"
            )
        if d.get("new_decoder_architecture"):
            raise ValueError(
                "falcon new_decoder_architecture (grouped-KV interleave, "
                "falcon-40b/180b) is not supported by the native core yet"
            )
        if not d.get("parallel_attn", True):
            raise ValueError(
                "falcon parallel_attn=false (sequential blocks) is not "
                "supported by the native falcon path"
            )
        if d.get("bias"):
            # our falcon layout is bias-free (like every released falcon);
            # loading a bias=true checkpoint would silently zero every
            # linear bias — refuse, don't drop
            raise ValueError(
                "falcon bias=true checkpoints are not supported by the "
                "native core; serve via the ollama/remote backends"
            )
        H, D = d["num_attention_heads"], d["hidden_size"]
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=D,
            n_layers=d["num_hidden_layers"], n_heads=H,
            n_kv_heads=1 if d.get("multi_query", True) else H,
            d_ff=d.get("ffn_hidden_size") or 4 * D,
            max_seq_len=d.get("max_position_embeddings", 2048),
            activation="gelu_exact", norm="layernorm",
            tie_embeddings=d.get("tie_word_embeddings", True),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=_parse_rope_scaling(d), parallel_block=True,
            norm_eps=d.get("layer_norm_epsilon", 1e-5),
        )
    if mt == "phi":
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=d["num_hidden_layers"], n_heads=d["num_attention_heads"],
            n_kv_heads=d.get("num_key_value_heads") or d["num_attention_heads"],
            d_ff=d["intermediate_size"],
            max_seq_len=d.get("max_position_embeddings", 2048),
            activation="gelu", norm="layernorm", use_bias=True,
            tie_embeddings=False,
            rotary_pct=d.get("partial_rotary_factor", 1.0),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=_parse_rope_scaling(d), parallel_block=True,
            lm_head_bias=True, norm_eps=d.get("layer_norm_eps", 1e-5),
        )
    if mt == "qwen3_moe":
        if not d.get("norm_topk_prob", False):
            # our routing renormalizes the top-k weights (softmax over the
            # selected logits == softmax-all + renorm); without the renorm
            # the weighting differs — refuse, don't serve drifted mixtures
            raise ValueError(
                "qwen3_moe with norm_topk_prob=false is not supported by "
                "the native core (routing weights would differ)"
            )
        if d.get("decoder_sparse_step", 1) != 1 or d.get("mlp_only_layers"):
            raise ValueError(
                "qwen3_moe with dense interleaved layers "
                "(decoder_sparse_step != 1 / mlp_only_layers) is not "
                "supported by the native core"
            )
        if d.get("attention_bias"):
            raise ValueError(
                "qwen3_moe attention_bias=true is not supported by the "
                "native core (o_proj bias)"
            )
        H = d["num_attention_heads"]
        # Qwen3MoeConfig has NO head_dim parameter — transformers falls
        # back to hidden_size // num_attention_heads when absent (unlike
        # dense Qwen3Config's 128 default)
        hd = d.get("head_dim")
        kw3: dict = dict(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=d["num_hidden_layers"], n_heads=H,
            # class default is 4, NOT n_heads (the family-default rule)
            n_kv_heads=d.get("num_key_value_heads", 4),
            # expert width, not the (unused) dense intermediate_size
            d_ff=d["moe_intermediate_size"],
            max_seq_len=d.get("max_position_embeddings", 32768),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=_parse_rope_scaling(d, 32768),
            norm_eps=d.get("rms_norm_eps", 1e-6),
            tie_embeddings=d.get("tie_word_embeddings", False),
            qk_norm=True,
            n_experts=d["num_experts"],
            n_experts_per_tok=d.get("num_experts_per_tok", 8),
        )
        if d.get("use_sliding_window") and d.get("sliding_window"):
            # unlike dense qwen, Qwen3Moe modeling never reads
            # max_window_layers — it windows EVERY layer when enabled
            kw3["sliding_window"] = d["sliding_window"]
        if hd and hd != d["hidden_size"] // H:
            kw3["head_dim_override"] = hd
        return ModelConfig(**kw3)
    if mt == "olmo2":
        if d.get("attention_bias"):
            # same refuse-don't-drop rule as the llama branch: the o_proj
            # bias has no slot in our layout
            raise ValueError(
                "olmo2 checkpoints with attention_bias=true are not "
                "supported by the native core; serve via the ollama/remote "
                "backends"
            )
        H = d["num_attention_heads"]
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=d["num_hidden_layers"], n_heads=H,
            n_kv_heads=d.get("num_key_value_heads") or H,
            d_ff=d["intermediate_size"],
            max_seq_len=d.get("max_position_embeddings", 2048),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=_parse_rope_scaling(d),
            norm_eps=d.get("rms_norm_eps", 1e-5),
            tie_embeddings=d.get("tie_word_embeddings", False),
            # olmo2 blocks norm only their OUTPUTS, and RMS-normalize the
            # WHOLE q/k projection before the head reshape
            post_norms=True, no_pre_norms=True,
            qk_norm=True, qk_norm_full=True,
        )
    if mt == "stablelm":
        if d.get("use_parallel_residual"):
            raise ValueError(
                "stablelm use_parallel_residual=true is not supported by "
                "the native core's stablelm path"
            )
        if d.get("qk_layernorm"):
            raise ValueError(
                "stablelm qk_layernorm=true (per-head LayerNorm) is not "
                "supported by the native core"
            )
        H = d["num_attention_heads"]
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=d["num_hidden_layers"], n_heads=H,
            n_kv_heads=d.get("num_key_value_heads") or H,
            d_ff=d["intermediate_size"],
            max_seq_len=d.get("max_position_embeddings", 4096),
            norm="layernorm",  # biased LNs over the llama tensor layout
            rotary_pct=d.get("partial_rotary_factor", 0.25),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=_parse_rope_scaling(d, 4096),
            qkv_bias=d.get("use_qkv_bias", False),
            tie_embeddings=d.get("tie_word_embeddings", False),
            norm_eps=d.get("layer_norm_eps", 1e-5),
        )
    if mt == "phi3":
        # architecturally a llama-branch model (the loader un-fuses
        # qkv_proj / gate_up_proj); partial rotary + optional window
        H = d["num_attention_heads"]
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=d["num_hidden_layers"], n_heads=H,
            n_kv_heads=d.get("num_key_value_heads") or H,
            d_ff=d["intermediate_size"],
            max_seq_len=d.get("max_position_embeddings", 4096),
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=_parse_rope_scaling(d, 4096),  # longrope refuses
            rotary_pct=d.get("partial_rotary_factor", 1.0),
            norm_eps=d.get("rms_norm_eps", 1e-5),
            tie_embeddings=d.get("tie_word_embeddings", False),
            sliding_window=d.get("sliding_window"),
        )
    if mt == "gemma3":
        raise ValueError(
            "gemma3 multimodal configs are not supported; extract the "
            "text_config (model_type gemma3_text) or serve via the "
            "ollama/remote backends"
        )
    if mt == "gemma3_text":
        L = d["num_hidden_layers"]
        types = d.get("layer_types")
        if types:
            sliding = {i for i, t in enumerate(types)
                       if t == "sliding_attention"}
            # recover a periodic (every, residues) description; gemma-3
            # ships 5-local-1-global (period 6)
            for p in range(1, min(len(types), 12) + 1):
                residues = tuple(sorted({i % p for i in sliding}))
                if all((i % p in residues) == (i in sliding)
                       for i in range(len(types))):
                    every, res = p, residues
                    break
            else:
                raise ValueError(
                    "gemma3 layer_types pattern is not periodic; cannot "
                    "represent it"
                )
        else:
            # no layer_types (older transformers writers): the pattern key
            # is sliding_window_pattern (Gemma3TextConfig default 6),
            # is_sliding = (i+1) % pattern != 0 — i.e. every pattern-th
            # layer is global, the rest are local. Hardcoding 5-local-1-
            # global here would silently mis-mask (and mis-rope) any
            # checkpoint shipping a non-default pattern.
            pattern = int(d.get("sliding_window_pattern") or 6)
            every = max(pattern, 1)
            res = tuple(r for r in range(every) if (r + 1) % every != 0)
        window = d.get("sliding_window", 4096)
        if not res:
            # no sliding layers at all (e.g. a long-context fine-tune):
            # every-1 + the window set would make make_layer_mask window
            # EVERY layer — disable the window instead
            window, every, res = None, 1, ()
        return ModelConfig(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=L, n_heads=d["num_attention_heads"],
            n_kv_heads=d.get("num_key_value_heads")
            or d["num_attention_heads"],
            d_ff=d["intermediate_size"],
            max_seq_len=d.get("max_position_embeddings", 131072),
            activation="geglu", embedding_scale=True, norm_plus_one=True,
            post_norms=True, qk_norm=True,
            attn_scale=d.get("query_pre_attn_scalar", 256),
            attn_logit_softcap=d.get("attn_logit_softcapping"),
            logits_softcap=d.get("final_logit_softcapping"),
            rope_theta=d.get("rope_theta", 1000000.0),
            local_rope_theta=d.get("rope_local_base_freq", 10000.0),
            rope_scaling=_parse_rope_scaling(d, 131072),
            norm_eps=d.get("rms_norm_eps", 1e-6),
            tie_embeddings=d.get("tie_word_embeddings", True),
            # every/residues stay decoupled from the window: even with the
            # window disabled they still drive the local/global ROPE split
            sliding_window=window,
            sliding_window_every=every,
            sliding_window_residues=res,
            **({"head_dim_override": hd} if (
                hd := d.get("head_dim", 256)
            ) and hd != d["hidden_size"] // d["num_attention_heads"]
               else {}),
        )
    if mt in ("llama", "mistral", "qwen2", "qwen3", "gemma", "gemma2",
              "mixtral"):
        n_heads = d["num_attention_heads"]
        # transformers serializes config.json as a DIFF against each
        # Config class's defaults — absent keys mean the FAMILY default
        # (values introspected from the installed transformers; a wrong
        # fallback here silently drifts every norm / truncates context)
        gemma_like = mt in ("gemma", "gemma2")
        hd = d.get("head_dim",
                   {"gemma": 256, "gemma2": 256, "qwen3": 128}.get(mt))
        default_maxpos = {"llama": 2048, "mistral": 131072,
                          "mixtral": 131072, "qwen2": 32768,
                          "qwen3": 32768, "gemma": 8192, "gemma2": 8192}[mt]
        kw: dict = dict(
            name=nm, vocab_size=d["vocab_size"], d_model=d["hidden_size"],
            n_layers=d["num_hidden_layers"], n_heads=n_heads,
            n_kv_heads=d.get("num_key_value_heads") or n_heads,
            d_ff=d["intermediate_size"],
            max_seq_len=d.get("max_position_embeddings", default_maxpos),
            rope_theta=d.get("rope_theta",
                             1000000.0 if mt == "mixtral" else 10000.0),
            # every family defaults rms_norm_eps=1e-6 EXCEPT mixtral (1e-5)
            norm_eps=d.get("rms_norm_eps",
                           1e-5 if mt == "mixtral" else 1e-6),
            tie_embeddings=d.get("tie_word_embeddings", gemma_like),
            qkv_bias=mt == "qwen2",
            qk_norm=mt == "qwen3",
        )
        if (scaling := _parse_rope_scaling(d, default_maxpos)) is not None:
            kw["rope_scaling"] = scaling
        if d.get("attention_bias"):
            # HF attention_bias puts biases on q/k/v AND o_proj; our
            # llama-branch layout carries q/k/v biases only (qwen2 style),
            # so the o_proj bias would be silently dropped — refuse rather
            # than serve offset logits
            raise ValueError(
                "llama-family checkpoints with attention_bias=true are not "
                "supported by the native core (o_proj bias); serve via the "
                "ollama/remote backends"
            )
        if hd and hd != d["hidden_size"] // n_heads:
            kw["head_dim_override"] = hd
        if mt == "mistral":
            # an ABSENT key means MistralConfig's class default (4096) —
            # the same "config.json is a diff against class defaults" rule
            # gemma-2 follows below; an explicit null stays disabled
            window = d.get("sliding_window", 4096)
            if window:
                kw["sliding_window"] = window
        elif mt == "mixtral" and d.get("sliding_window"):
            # MixtralConfig's class default is null — absent means off
            kw["sliding_window"] = d["sliding_window"]
        if (mt in ("qwen2", "qwen3") and d.get("use_sliding_window")
                and d.get("sliding_window")):
            mwl = int(d.get("max_window_layers") or 0)
            if mwl <= 0:
                kw["sliding_window"] = d["sliding_window"]
            elif mwl >= int(d["num_hidden_layers"]):
                # HF windows only layers >= max_window_layers, so a cap at
                # (or past) the layer count windows NOTHING — full
                # attention is bit-exact, not a compromise: stay silent
                pass
            else:
                # HF windows only layers >= max_window_layers; our config
                # windows EVERY layer, so a partial-window checkpoint
                # (max_window_layers > 0) is served full-attention instead —
                # exact for prompts within the window and matches HF on the
                # majority (first) layers, vs. silently wrong everywhere.
                # Say so at serve time: this is a fidelity compromise.
                logger.warning(
                    "%s: dropping the partial sliding-window schedule "
                    "(sliding_window=%s, max_window_layers=%s) — serving "
                    "full attention on every layer; long-context logits "
                    "will diverge from HF beyond the window",
                    nm, d.get("sliding_window"), d.get("max_window_layers"),
                )
        if mt in ("gemma", "gemma2"):
            act = d.get("hidden_activation") or d.get("hidden_act") or "gelu_pytorch_tanh"
            kw.update(
                activation="geglu" if act.startswith("gelu") else act,
                embedding_scale=True, norm_plus_one=True,
            )
        if mt == "gemma2":
            # transformers serializes config.json as a DIFF against class
            # defaults — an absent key means the Gemma2Config DEFAULT
            # (50/30/256/4096), NOT disabled; an explicit null stays None
            window = d.get("sliding_window", 4096)
            kw.update(
                post_norms=True,
                attn_logit_softcap=d.get("attn_logit_softcapping", 50.0),
                logits_softcap=d.get("final_logit_softcapping", 30.0),
                attn_scale=d.get("query_pre_attn_scalar", 256),
                # HF Gemma2: is_sliding = not bool(layer_idx % 2) — even
                # layers window, odd attend fully
                sliding_window=window,
                sliding_window_every=2 if window else 1,
            )
        if mt == "mixtral":
            kw.update(n_experts=d["num_local_experts"],
                      n_experts_per_tok=d.get("num_experts_per_tok", 2))
        return ModelConfig(**kw)
    raise ValueError(
        f"unsupported model_type {mt!r} in config.json — native serving "
        f"covers gpt2/llama/mistral/qwen2/gemma/mixtral/phi/gpt_neox/gptj; "
        f"other architectures can be served via the ollama/remote backends"
    )


def config_for_checkpoint(path: str | Path, name: str | None = None) -> ModelConfig:
    """Resolve a checkpoint DIRECTORY to a ModelConfig from its own
    metadata: a native save (model_config.json, our field names) or an HF
    checkpoint (config.json). This is what lets ``serve-tpu --model auto
    --checkpoint <dir>`` serve architectures with no registry entry."""
    path = Path(path)
    native = path / "model_config.json"
    if native.exists():
        d = json.loads(native.read_text())
        known = {f.name for f in fields(ModelConfig)}
        unknown = sorted(set(d) - known)
        if unknown:
            # a checkpoint saved by a newer version may carry architecture
            # switches this build doesn't know; dropping them silently
            # would serve wrong logits with no signal
            logger.warning(
                "%s: ignoring unknown model_config.json keys %s — if these "
                "are architecture switches from a newer writer, the served "
                "logits will diverge",
                native, unknown,
            )
        return ModelConfig(**{k: v for k, v in d.items() if k in known})
    hf = path / "config.json"
    if hf.exists():
        return config_from_hf(json.loads(hf.read_text()), name=name)
    raise FileNotFoundError(
        f"no model_config.json or config.json under {path} — cannot "
        f"synthesize a model config for this checkpoint"
    )


def resolve_model_config(model, checkpoint_path: str | None = None) -> ModelConfig:
    """THE model-resolution rule shared by the engine and the pipeline
    stage runner: a ModelConfig passes through; a registry name resolves
    via get_config; an unknown name (or the 'auto' sentinel) with a
    checkpoint falls back to the checkpoint's own config
    (config_for_checkpoint) — the reference's AutoModel any-checkpoint
    capability."""
    if isinstance(model, ModelConfig):
        return model
    try:
        return get_config(model or "auto")
    except KeyError:
        if not checkpoint_path:
            raise
        return config_for_checkpoint(
            checkpoint_path,
            name=None if model in (None, "", "auto") else model,
        )


def get_config(name: str, **overrides) -> ModelConfig:
    """Resolve a model name to a config, with the reference's both-ways fuzzy
    match (`services.py:136-151`): exact key, else substring either way."""
    key = name.lower().strip()
    if key in CONFIGS:
        cfg = CONFIGS[key]
    else:
        short = key.split("/")[-1]
        flat = lambda s: s.replace("-", "").replace("_", "").replace(".", "")
        # tiny-* test presets never match a real checkpoint name unless the
        # query itself says "tiny"
        pool = {
            k: c for k, c in CONFIGS.items()
            if "tiny" in short or not k.startswith("tiny-")
        }
        # tiers: exact short name > key contained in query > query contained
        # in key. Tie-breaks differ by direction: when the KEY is inside the
        # query (tier 2), the longest key is the most specific match; when
        # the QUERY is inside several keys (tier 3, e.g. "llama-3" matching
        # both -8b and -70b), the SHORTEST key is the family default — the
        # longest would silently resolve a bare family name to its biggest
        # member
        tiers = (
            ([k for k in pool if k == short or flat(k) == flat(short)], max),
            ([k for k in pool if flat(k) in flat(short)], max),
            ([k for k in pool if flat(short) in flat(k)], min),
        )
        hit = next(((t, pick) for t, pick in tiers if t), None)
        if hit is None:
            raise KeyError(f"no model config matches {name!r}; known: {sorted(CONFIGS)}")
        t, pick = hit
        cfg = pool[pick(t, key=len)]
    return replace(cfg, **overrides) if overrides else cfg
