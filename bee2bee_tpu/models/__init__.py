"""Model families: pure-JAX transformer definitions with explicit param
pytrees and per-param partition rules.

One configurable core (`core.py`) covers every family the serving ladder
needs (BASELINE.md configs 1-5): GPT-2 (learned positions, MHA, gelu),
Llama/Mistral/Zephyr (RoPE, GQA, silu-gated MLP, RMSNorm), Gemma (RoPE,
geglu, embedding scaling), Mixtral (Llama core + top-2 MoE). The reference
delegates all of this to `transformers` on torch (reference hf.py:23-44);
here the model IS the framework's code, jit-compiled, with layer params
stacked for `lax.scan` so compile time is O(1) in depth.
"""

from .config import CONFIGS, ModelConfig, get_config  # noqa: F401
from .core import forward, init_params  # noqa: F401
from .partition import partition_specs  # noqa: F401
