"""The transformer core: init + forward for every supported family.

Design (TPU-first, not a port of reference hf.py):

- **Stacked layer params + `lax.scan`**: all per-layer weights carry a
  leading `n_layers` dim and the layer loop is a `lax.scan`, so XLA traces
  one layer body regardless of depth — compile time and HLO size are O(1)
  in n_layers.
- **Single forward for prefill and decode**: the same function handles a
  [B, T] chunk against a fixed-capacity KV cache at a given offset. T=1 is
  the decode step; T=bucket is prefill. Static shapes everywhere — the
  cache is preallocated at `max_seq_len`, masking handles validity.
- **GQA by construction**: K/V heads are repeated via reshape-broadcast
  (no materialized repeat when XLA fuses).
- **bfloat16 compute, f32 accumulations** where it matters (attention
  logits, softmax, norms, router logits).

The param tree is a flat-ish nested dict; see init_params for the schema.
Partition rules over the same paths live in partition.py.
"""

from __future__ import annotations

import functools
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from .config import ModelConfig

Params = dict[str, Any]


# ---------------------------------------------------------------- init


def _dense_init(key, shape, scale=None, dtype=jnp.float32):
    # fan-in is the second-to-last dim: layer-stacked weights are [L, in, out]
    fan_in = shape[-2] if len(shape) > 1 else shape[0]
    scale = scale if scale is not None else 1.0 / math.sqrt(fan_in)
    return jax.random.normal(key, shape, dtype) * scale


def matmul_params_per_token(cfg: ModelConfig) -> int:
    """Matmul weight elements each token position streams through one
    forward — the ``2·N`` half of the engine economics plane's FLOPs
    model (engine/introspect.py): every counted element costs one
    multiply + one add per position.

    Counted: q/k/v/o projections, the dense MLP (gated → 3 matrices), the
    lm head (tied or not — the logits matmul runs either way), and for
    MoE the router plus only the ``n_experts_per_tok`` ACTIVE experts —
    what a routed token actually pays, matching the "routed" impl (the
    "dense" correctness impl physically computes all E experts, but MFU
    is defined on the model's useful math, not an impl's redundancy).
    Excluded: embeddings lookup, norms, biases, rope — O(D) noise next
    to the O(D²) terms."""
    D, F, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    H, Hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    attn = D * (H * hd) + 2 * D * (Hkv * hd) + (H * hd) * D
    gated = cfg.activation in ("silu", "geglu")
    mlp_one = (3 if gated else 2) * D * F
    if cfg.is_moe:
        mlp = D * cfg.n_experts + cfg.n_experts_per_tok * mlp_one
    else:
        mlp = mlp_one
    return L * (attn + mlp) + D * cfg.vocab_size


def init_params(cfg: ModelConfig, key, dtype=jnp.bfloat16) -> Params:
    """Random-init params with the layout the whole framework shares.

    Schema (leading L = n_layers stacked dim):
      tok_embed [V, D]; pos_embed [P, D] (learned-pos only);
      final_norm {scale[D], (bias[D])}; lm_head [D, V] (untied only,
      + lm_head_bias [V] when cfg.lm_head_bias — phi)
      layers/
        ln1.scale|bias [L, D]
        attn: wq [L, D, H*hd], wk|wv [L, D, Hkv*hd], wo [L, H*hd, D]
              (+ bq, bk, bv [L, ...], bo [L, D] when use_bias)
        ln2.scale|bias [L, D] (absent for shared-norm parallel blocks — phi)
        dense mlp: w_up [L, D, F], w_down [L, F, D], (w_gate [L, D, F])
                   (+ b_up [L, F], b_down [L, D])
        moe: router [L, D, E], experts w_up|w_gate [L, E, D, F],
             w_down [L, E, F, D]
    """
    D, F, V, L = cfg.d_model, cfg.d_ff, cfg.vocab_size, cfg.n_layers
    H, Hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    keys = iter(jax.random.split(key, 32))

    def dense(shape, scale=None):
        return _dense_init(next(keys), shape, scale, dtype)

    params: Params = {
        "tok_embed": _dense_init(next(keys), (V, D), scale=0.02, dtype=dtype),
    }
    if cfg.pos_embedding == "learned":
        params["pos_embed"] = _dense_init(next(keys), (cfg.max_seq_len, D), 0.02, dtype)
    if cfg.embedding_norm:
        params["embed_norm"] = {"scale": jnp.ones((D,), dtype)}
        if cfg.norm == "layernorm" and cfg.norm_bias:
            params["embed_norm"]["bias"] = jnp.zeros((D,), dtype)

    layers: Params = {
        "attn": {
            "wq": dense((L, D, H * hd)),
            "wk": dense((L, D, Hkv * hd)),
            "wv": dense((L, D, Hkv * hd)),
            "wo": dense((L, H * hd, D), scale=1.0 / math.sqrt(H * hd)),
        },
    }
    if not cfg.no_pre_norms:  # olmo2 blocks norm only their OUTPUTS
        layers["ln1"] = {"scale": jnp.ones((L, D), dtype)}
        if not cfg.parallel_block or cfg.parallel_norms == 2:
            # sequential blocks AND neox-style dual-norm parallel blocks
            # have ln2; only phi's shared-norm parallel blocks drop it
            layers["ln2"] = {"scale": jnp.ones((L, D), dtype)}
    if cfg.post_norms:  # gemma-2: norms on the attn/mlp outputs too
        layers["ln1_post"] = {"scale": jnp.ones((L, D), dtype)}
        layers["ln2_post"] = {"scale": jnp.ones((L, D), dtype)}
    if cfg.norm == "layernorm" and cfg.norm_bias:
        for ln in ("ln1", "ln2", "ln1_post", "ln2_post"):
            if ln in layers:
                layers[ln]["bias"] = jnp.zeros((L, D), dtype)
    if cfg.use_bias or cfg.qkv_bias:
        layers["attn"]["bq"] = jnp.zeros((L, H * hd), dtype)
        layers["attn"]["bk"] = jnp.zeros((L, Hkv * hd), dtype)
        layers["attn"]["bv"] = jnp.zeros((L, Hkv * hd), dtype)
    if cfg.qk_norm:  # qwen3: per-head scales; olmo2: full-width scales
        qn = (H * hd, Hkv * hd) if cfg.qk_norm_full else (hd, hd)
        layers["attn"]["q_norm"] = jnp.ones((L, qn[0]), dtype)
        layers["attn"]["k_norm"] = jnp.ones((L, qn[1]), dtype)
    if cfg.use_bias:  # qwen2 (qkv_bias) has NO output-projection bias
        layers["attn"]["bo"] = jnp.zeros((L, D), dtype)

    gated = cfg.activation in ("silu", "geglu")
    if cfg.is_moe:
        E = cfg.n_experts
        moe = {
            "router": dense((L, D, E)),
            "w_up": dense((L, E, D, F)),
            "w_down": dense((L, E, F, D), scale=1.0 / math.sqrt(F)),
        }
        if gated:
            moe["w_gate"] = dense((L, E, D, F))
        layers["moe"] = moe
    else:
        mlp = {
            "w_up": dense((L, D, F)),
            "w_down": dense((L, F, D), scale=1.0 / math.sqrt(F)),
        }
        if gated:
            mlp["w_gate"] = dense((L, D, F))
        if cfg.use_bias or cfg.mlp_bias:
            mlp["b_up"] = jnp.zeros((L, F), dtype)
            mlp["b_down"] = jnp.zeros((L, D), dtype)
        layers["mlp"] = mlp

    params["layers"] = layers
    params["final_norm"] = {"scale": jnp.ones((D,), dtype)}
    if cfg.norm == "layernorm" and cfg.norm_bias:
        params["final_norm"]["bias"] = jnp.zeros((D,), dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = dense((D, V))
        if cfg.lm_head_bias:  # phi: untied head carries a bias
            params["lm_head_bias"] = jnp.zeros((V,), dtype)
    return params


# ---------------------------------------------------------------- ops


def _norm(x, p, cfg: ModelConfig):
    xf = x.astype(jnp.float32)
    if cfg.norm == "rmsnorm":
        xf = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + cfg.norm_eps)
    else:
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        xf = (xf - mean) * lax.rsqrt(var + cfg.norm_eps)
    out = xf.astype(x.dtype) * p["scale"]
    if "bias" in p:
        out = out + p["bias"]
    return out


def scale_rope_freqs(freqs, scaling: tuple | None, theta: float | None = None,
                     rot: int | None = None):
    """Frequency-domain RoPE scaling (cfg.rope_scaling).

    "linear": all frequencies divided by the factor — position
    interpolation. "llama3" (llama-3.1+): long wavelengths (> original
    context / low_freq_factor) get the full division, short wavelengths
    (< original / high_freq_factor) stay untouched, the band between
    interpolates. "yarn": NTK-by-parts — a linear ramp over the rotary
    DIMENSIONS (not wavelengths) between full interpolation and no
    scaling, with the ramp bounds derived from beta_fast/beta_slow
    rotations at the original context (theta and rot required). All must
    match transformers' _compute_*_parameters exactly or every position's
    rotation drifts. The yarn attention_factor (cos/sin magnitude) is
    applied in _rope, not here."""
    if scaling is None:
        return freqs
    if scaling[0] == "linear":
        return freqs / scaling[1]
    if scaling[0] == "yarn":
        if theta is None or rot is None:
            raise ValueError(
                "yarn rope scaling needs theta and rot (the ramp bounds "
                "are dimension- and base-dependent)"
            )
        _, factor, _af, beta_fast, beta_slow, orig, truncate = scaling

        def corr_dim(n_rot):
            return (rot * math.log(orig / (n_rot * 2 * math.pi))
                    ) / (2 * math.log(theta))

        low, high = corr_dim(beta_fast), corr_dim(beta_slow)
        if truncate:
            low, high = math.floor(low), math.ceil(high)
        low, high = max(low, 0), min(high, rot - 1)
        if low == high:
            high += 0.001
        ramp = jnp.clip(
            (jnp.arange(rot // 2, dtype=jnp.float32) - low) / (high - low),
            0.0, 1.0,
        )
        extrap = 1.0 - ramp  # 1 = keep the base frequency (extrapolation)
        return (freqs / factor) * (1.0 - extrap) + freqs * extrap
    _, factor, low_f, high_f, orig = scaling
    low_wavelen = orig / low_f
    high_wavelen = orig / high_f
    wavelen = 2.0 * math.pi / freqs
    smooth = (orig / wavelen - low_f) / (high_f - low_f)
    smoothed = (1.0 - smooth) * freqs / factor + smooth * freqs
    return jnp.where(
        wavelen > low_wavelen, freqs / factor,
        jnp.where(wavelen < high_wavelen, freqs, smoothed),
    )


def _qk_rmsnorm(x, scale, eps: float):
    """Per-head RMSNorm over head_dim (qwen3's q_norm/k_norm).
    x: [B, T, H, hd]; scale: [hd] (shared across heads)."""
    xf = x.astype(jnp.float32)
    xf = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return xf.astype(x.dtype) * scale


def _rope(x, positions, theta: float, rot: int | None = None,
          style: str = "half", scaling: tuple | None = None):
    """Rotary embedding. x: [B, T, H, hd]; positions: [B, T].

    rot < hd rotates only the FIRST rot dims and passes the tail through
    unchanged (phi/gpt-neox/gpt-j partial rotary; cfg.rotary_dim is the
    one place the count is derived). style="half" rotates the (first,
    second) halves of the rotary block together (llama/neox/phi);
    "interleaved" rotates adjacent pairs (x[2i], x[2i+1]) — gpt-j's
    rotate_every_two. Both share the same per-pair frequencies."""
    hd = x.shape[-1]
    rot = hd if rot is None else rot
    xr, tail = x[..., :rot], x[..., rot:]
    freqs = 1.0 / (theta ** (jnp.arange(0, rot, 2, dtype=jnp.float32) / rot))
    freqs = scale_rope_freqs(freqs, scaling, theta=theta, rot=rot)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, T, rot/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    xf = xr.astype(jnp.float32)
    if style == "interleaved":
        x1 = xf[..., 0::2]  # [B, T, H, rot/2]
        x2 = xf[..., 1::2]
        r1 = x1 * cos - x2 * sin
        r2 = x2 * cos + x1 * sin
        out = jnp.stack([r1, r2], axis=-1).reshape(xf.shape)
    else:
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    if scaling is not None and scaling[0] == "yarn":
        # yarn's attention temperature: HF multiplies cos AND sin by the
        # attention_factor, i.e. the whole rotated block scales (the
        # non-rotary tail stays untouched)
        out = out * scaling[2]
    out = out.astype(x.dtype)
    return out if rot == hd else jnp.concatenate([out, tail], axis=-1)


def _activate(up, gate, cfg: ModelConfig):
    if cfg.activation == "silu":
        return jax.nn.silu(gate) * up
    if cfg.activation == "geglu":
        return jax.nn.gelu(gate, approximate=True) * up
    if cfg.activation == "gelu_exact":  # gpt-neox: erf, not tanh approx
        return jax.nn.gelu(up, approximate=False)
    return jax.nn.gelu(up, approximate=True)


def alibi_slopes(n_heads: int) -> list[float]:
    """Per-head ALiBi slopes (the train-short-test-long bias of bloom/
    mpt): geometric sequence 2^(-8i/n) for power-of-two head counts, with
    HF's interpolation for the remainder otherwise — must match
    transformers' build_alibi_tensor exactly or logits drift."""
    n = 2 ** math.floor(math.log2(n_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
    slopes = [base ** (i + 1) for i in range(n)]
    if n < n_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * n) - 3)))
        slopes += [extra_base ** (2 * i + 1) for i in range(n_heads - n)]
    return slopes


def _attention(q, k, v, mask, cfg: ModelConfig):
    """q: [B, T, H, hd]; k, v: [B, S, Hkv, hd]; mask: [B, 1, T, S] bool."""
    B, T, H, hd = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    group = H // Hkv
    q = q.reshape(B, T, Hkv, group, hd)
    logits = jnp.einsum("btkgh,bskh->bkgts", q, k).astype(jnp.float32)
    # gemma-2 overrides the score denominator (query_pre_attn_scalar)
    logits = logits / math.sqrt(cfg.attn_scale or hd)
    if cfg.attn_logit_softcap:  # gemma-2: tanh cap BEFORE masking
        c = cfg.attn_logit_softcap
        logits = jnp.tanh(logits / c) * c
    if cfg.pos_embedding == "alibi":
        # + slope_h * key_position: softmax is shift-invariant per query
        # row, so the absolute-position form equals the relative -m*(i-j)
        # bias (and is exactly what HF bloom adds); masked slots are
        # overwritten below, so cache positions work unchanged
        slopes = jnp.asarray(alibi_slopes(H), jnp.float32).reshape(Hkv, group)
        logits = logits + (slopes[None, :, :, None, None]
                           * jnp.arange(S, dtype=jnp.float32))
    # mask [B,1,T,S] -> broadcast over (kv_head, group) dims
    logits = jnp.where(mask[:, :, None, :, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgts,bskh->btkgh", probs, v)
    return out.reshape(B, T, H * hd)


def _quantized_page_write(pool, scale, blk, slot, wslot, xT):
    """Quantize-on-write scatter for ONE layer of an int8 paged pool —
    the models/quant.py symmetric amax recipe at (page, kv-head)
    granularity.

    ``pool`` [Hkv, NB, BS, hd] int8; ``scale`` [Hkv, NB] f32;
    ``blk``/``slot`` [B, T] the position→(page, slot) map WITH the
    write-floor/ceil null redirects already applied (so CoW donor pages
    are never touched — redirected positions land in the null block 0);
    ``wslot`` [B, T] each position's index into the chunk's page window
    (positions // BS - offset // BS); ``xT`` [Hkv, B, T, hd] the chunk's
    freshly projected K or V, head-major like the pool.

    A page's scale is a RUNNING MAX over its tenancy: a write that
    raises the page's amax requantizes the page's existing int8 content
    under the grown scale (bounded re-rounding noise — at most one
    re-round per scale growth; scales never shrink until the allocator
    recycles the block and the scheduler zeroes its scale entry, so a
    recycled block's previous tenant can never inflate the new one).
    Touched pages are deduplicated through the chunk's page window
    before the gather/rescatter, so per-step requantization traffic is
    O(pages written) — one page per row on decode — not O(T) full-page
    copies. Returns (new_pool, new_scale)."""
    Hkv, NB, BS, hd = pool.shape
    B, T = blk.shape
    # a T-position chunk at an arbitrary slot offset straddles at most
    # this many pages — the window the touched-page dedup scatters into
    # (wslot values are < P by construction: (off+T-1)//BS - off//BS)
    P = (T + BS - 2) // BS + 1
    xf = xT.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1) * (1.0 / 127.0)  # [Hkv, B, T]
    # scatter-max per (head, page): redirected positions only ever grow
    # the null block's scale (garbage page by design)
    cand = jnp.zeros((Hkv, NB), jnp.float32).at[:, blk].max(amax)
    new_scale = jnp.maximum(scale, cand)
    safe = jnp.where(new_scale > 0.0, new_scale, 1.0)
    # dedup touched pages: window slot w holds ONE page id (rows own
    # disjoint blocks; fully redirected slots keep the null block 0),
    # so the page gather/rescatter below moves each page once
    pg_blk = jnp.zeros((B, P), jnp.int32).at[
        jnp.arange(B, dtype=jnp.int32)[:, None], wslot
    ].max(blk)
    # requantize existing content under the (possibly) grown scale —
    # but ONLY when some page actually grew (lax.cond, a real branch):
    # steady-state decode (token amax under the page's running max, the
    # common case once a page warms up) skips the page read-modify-write
    # entirely and pays just the slot scatter, like the bf16 path.
    # Inside the taken branch, ratio == 1 where unchanged (rint(int *
    # 1.0) is exact), < 1 where grown, 0 for a freshly reset page
    # (scale 0 → stale bytes zeroed before the new tenant's first read)
    def _requant(p):
        ratio = scale / safe  # [Hkv, NB]
        pages = p[:, pg_blk].astype(jnp.float32)  # [Hkv, B, P, BS, hd]
        rq = jnp.clip(
            jnp.rint(pages * ratio[:, pg_blk][..., None, None]), -127, 127
        ).astype(jnp.int8)
        return p.at[:, pg_blk].set(rq)

    out = lax.cond(jnp.any(cand > scale), _requant, lambda p: p, pool)
    # quantize the chunk's values under the new page scales and scatter
    # into their slots (distinct (page, slot) pairs except the null block)
    q = jnp.clip(
        jnp.rint(xf / safe[:, blk][..., None]), -127, 127
    ).astype(jnp.int8)
    return out.at[:, blk, slot].set(q), new_scale


def matmul(x, w):
    """x @ w where w may be an int8 weight-only quantized subtree
    {"q": int8 [..., in, out], "s": f32 [..., out]} (models/quant.py).
    Per-out-channel scales commute with the dot, so dequant applies to
    the OUTPUT — XLA fuses the int8 convert into the operand read and
    the weights stream from HBM at half the bf16 bytes."""
    if isinstance(w, dict) and "q" in w:
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def _lora_rows(ab, ids, scale):
    """Gather one layer's per-ROW adapter factors: ``ab`` is the pool's
    stacked {"a": [N, din, r], "b": [N, r, dout]} slice for this layer,
    ``ids`` [B] each row's pool slot (0 = the reserved null adapter,
    all-zero factors), ``scale`` [N] each slot's alpha/rank scaling.
    Returns (a [B, din, r], b [B, r, dout], s [B])."""
    return ab["a"][ids], ab["b"][ids], scale[ids]


def lora_matmul(x, w, name, lora):
    """The multi-adapter serving hook around ``matmul``: base projection
    plus each row's low-rank delta ``s * (x @ A) @ B`` (adapters/pool.py
    holds the stacked factors; train/lora.py defines the merge math this
    must agree with). ``lora`` is None (plain matmul — the trace is
    byte-identical to the pre-adapter graph) or {"ab": per-layer target
    dict, "ids": [B], "scale": [N]}; a target absent from the pool passes
    through untouched. Rows mapped to slot 0 gather the null adapter's
    zero factors, so adapter-less rows in a mixed batch stay exact (the
    batch-level skip for ALL-baseline batches lives in the scheduler,
    same per-row gating discipline as spec decode). The rank-r einsums
    run in f32 like merge_lora's delta, then cast back — x is [B, T, din]
    everywhere this is called (the batch dim is the row identity)."""
    out = matmul(x, w)
    ab = None if lora is None else lora["ab"].get(name)
    if ab is None:
        return out
    a, b, s = _lora_rows(ab, lora["ids"], lora["scale"])
    xf = x.astype(jnp.float32)
    h = jnp.einsum("btd,bdr->btr", xf, a.astype(jnp.float32))
    delta = jnp.einsum("btr,bro->bto", h, b.astype(jnp.float32))
    return out + (delta * s[:, None, None]).astype(out.dtype)


def expert_einsum(spec, x, w, s_expand):
    """Expert-weight einsum with optional int8 quantization.

    MoE expert weights are [E, in, out] (per-layer slice); their scales
    are [E, out] (models/quant.py, amax over the in dim), which commute
    with the contraction exactly as in matmul(). `s_expand` reshapes the
    scale to broadcast against the einsum OUTPUT (the out/expert dims
    land in different positions per formulation — dense puts E next to
    last, routed inserts a capacity dim)."""
    if isinstance(w, dict) and "q" in w:
        out = jnp.einsum(spec, x, w["q"].astype(x.dtype))
        return out * s_expand(w["s"].astype(out.dtype))
    return jnp.einsum(spec, x, w)


def _mlp(x, p, cfg: ModelConfig, lora=None):
    up = lora_matmul(x, p["w_up"], "w_up", lora)
    if "b_up" in p:
        up = up + p["b_up"]
    gate = lora_matmul(x, p["w_gate"], "w_gate", lora) if "w_gate" in p else None
    h = _activate(up, gate, cfg)
    out = lora_matmul(h, p["w_down"], "w_down", lora)
    if "b_down" in p:
        out = out + p["b_down"]
    return out


def _moe_routed(x, p, cfg: ModelConfig):
    """Top-k expert MLP, GShard-style routed dispatch (static shapes).

    Tokens are split into GROUPS of cfg.moe_group_size; each group routes
    independently into per-expert capacity buffers [G, E, C, D] via a
    dispatch one-hot, each expert runs its MLP on only its buffers, and a
    combine einsum scatters weighted outputs back — k/E of the dense
    formulation's expert FLOPs. Grouping keeps capacity — and the
    [G, g, E, C] dispatch tensor — O(group size), not O(batch*seq): the
    ungrouped formulation is quadratic in token count and OOMs real
    sequence lengths. C = ceil(g*k/E * capacity factor); assignments past
    an expert's per-group capacity drop (combine weight zero), token-
    index-major priority; trailing pad tokens consume no capacity.
    Everything is einsum/one_hot/cumsum — no gather/scatter, fully
    differentiable, and the sharded-E einsums become all-to-alls over the
    `expert` mesh axis under the partitioner.
    """
    B, T, D = x.shape
    E, k = cfg.n_experts, cfg.n_experts_per_tok
    N = B * T
    g = min(cfg.moe_group_size, N)
    G = -(-N // g)  # ceil: last group padded with dead tokens
    Np = G * g
    C = min(g, int(math.ceil(g * k / E * cfg.moe_capacity_factor)))

    xf = x.reshape(N, D)
    valid = jnp.ones((N,), jnp.float32)
    if Np != N:
        xf = jnp.pad(xf, ((0, Np - N), (0, 0)))
        valid = jnp.pad(valid, (0, Np - N))
    xg = xf.reshape(G, g, D)
    vg = valid.reshape(G, g)

    logits = jnp.einsum("gnd,de->gne", xg, p["router"]).astype(jnp.float32)
    topv, topi = lax.top_k(logits, k)
    topp = jax.nn.softmax(topv, axis=-1)  # [G, g, k] renormalized

    oh = jax.nn.one_hot(topi, E, dtype=jnp.float32)  # [G, g, k, E]
    oh = oh * vg[:, :, None, None]  # pad tokens take no capacity
    ohf = oh.reshape(G, g * k, E)  # token-major, slot-minor priority
    pos_all = jnp.cumsum(ohf, axis=1) - ohf  # per-group running count
    # exact small integers in f32; one_hot wants integer positions
    pos = jnp.sum(pos_all * ohf, axis=-1).astype(jnp.int32)  # [G, g*k]
    keep = (pos < C).astype(jnp.float32)
    slot = jax.nn.one_hot(pos, C, dtype=jnp.float32) * keep[..., None]
    disp = (ohf[..., None] * slot[:, :, None, :]).reshape(G, g, k, E, C)
    combine = jnp.sum(disp * topp[..., None, None], axis=2)  # [G, g, E, C]
    disp_tok = jnp.sum(disp, axis=2)  # [G, g, E, C] 0/1

    xe = jnp.einsum("gnec,gnd->gecd", disp_tok.astype(x.dtype), xg)
    # out [G,E,C,*]: scales [E,*] broadcast as [E,1,*] over the C dim
    s_ec = lambda s: s[:, None, :]  # noqa: E731
    up = expert_einsum("gecd,edf->gecf", xe, p["w_up"], s_ec)
    gate = (
        expert_einsum("gecd,edf->gecf", xe, p["w_gate"], s_ec)
        if "w_gate" in p
        else None
    )
    h = _activate(up, gate, cfg)
    ye = expert_einsum("gecf,efd->gecd", h, p["w_down"], s_ec)  # [G, E, C, D]
    out = jnp.einsum("gnec,gecd->gnd", combine.astype(ye.dtype), ye)
    return out.reshape(Np, D)[:N].reshape(B, T, D)


def _moe(x, p, cfg: ModelConfig):
    """Top-k expert MLP, dense-einsum formulation.

    Every token computes logits over E experts; the top-k probs are
    renormalized and all experts run on all tokens with a weight mask —
    the XLA-friendly dense formulation (no gather/scatter, static shapes).
    Expert-parallel sharding splits the E dim across the `expert` mesh axis
    and XLA turns the weighted sum into a reduce over that axis.
    cfg.moe_impl="routed" switches to the capacity-grouped dispatch that
    only pays the routed FLOPs (_moe_routed); dense stays the reference
    check.
    """
    if cfg.moe_impl == "routed":
        return _moe_routed(x, p, cfg)
    B, T, D = x.shape
    E, k = cfg.n_experts, cfg.n_experts_per_tok
    logits = (x @ p["router"]).astype(jnp.float32)  # [B, T, E]
    topv, topi = lax.top_k(logits, k)
    topp = jax.nn.softmax(topv, axis=-1)  # renormalized over the top-k
    # dense per-expert weight [B, T, E]: scatter top-k probs via one-hot
    weights = jnp.sum(jax.nn.one_hot(topi, E, dtype=jnp.float32) * topp[..., None], axis=-2)
    # out [B,T,E,*]: scales [E,*] align with the trailing dims directly
    s_id = lambda s: s  # noqa: E731
    up = expert_einsum("btd,edf->btef", x, p["w_up"], s_id)
    if "w_gate" in p:
        gate = expert_einsum("btd,edf->btef", x, p["w_gate"], s_id)
    else:
        gate = None
    h = _activate(up, gate, cfg)  # [B, T, E, F]
    out = expert_einsum("btef,efd->bted", h, p["w_down"], s_id)
    return jnp.einsum("bted,bte->btd", out, weights.astype(out.dtype))


# ------------------------------------------------------- reusable blocks


def embed_tokens(params: Params, cfg: ModelConfig, input_ids, positions):
    """Token (+learned-pos) embedding. input_ids [B,T], positions [B,T]."""
    x = jnp.take(params["tok_embed"], input_ids, axis=0)
    if cfg.embedding_scale:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), x.dtype)
    if cfg.pos_embedding == "learned":
        x = x + jnp.take(params["pos_embed"], positions, axis=0)
    if cfg.embedding_norm:  # bloom: LayerNorm before block 0
        x = _norm(x, params["embed_norm"], cfg)
    return x


def transformer_block(
    lp: Params, cfg: ModelConfig, x, positions, mask, kv_hook=None,
    attn_fn=None, rope_local=None, lora=None,
):
    """One block. lp: a single layer's params (no leading L dim). x [B,T,D].

    kv_hook(k, v) -> (k_eff, v_eff), when given, intercepts the freshly
    projected K/V — the cached decode path uses it to write the chunk into
    the KV cache and attend over the cache instead. No hook = plain causal
    self-attention over the chunk (training/scoring/pipeline-stage path).

    attn_fn(q, k, v, mask, cfg, positions=positions) -> [B,T,H*hd] replaces
    the dense softmax attention — the sequence-parallel path passes ring
    attention here, the engine's flash path passes the pallas kernel
    (which derives per-batch cache offsets from `positions`).

    ``lora`` (multi-adapter serving, adapters/pool.py): one layer's
    stacked per-target A/B factors plus the batch's per-row slot ids —
    every projection goes through lora_matmul, which adds each row's
    low-rank delta after the (possibly quantized) base matmul.
    """
    B, T, _ = x.shape
    H, Hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    h = x if cfg.no_pre_norms else _norm(x, lp["ln1"], cfg)
    q = lora_matmul(h, lp["attn"]["wq"], "wq", lora)
    k = lora_matmul(h, lp["attn"]["wk"], "wk", lora)
    v = lora_matmul(h, lp["attn"]["wv"], "wv", lora)
    if "bq" in lp["attn"]:
        q = q + lp["attn"]["bq"]
        k = k + lp["attn"]["bk"]
        v = v + lp["attn"]["bv"]
    if "q_norm" in lp["attn"] and cfg.qk_norm_full:
        # olmo2: RMSNorm over the WHOLE projection width, before reshape
        q = _qk_rmsnorm(q, lp["attn"]["q_norm"], cfg.norm_eps)
        k = _qk_rmsnorm(k, lp["attn"]["k_norm"], cfg.norm_eps)
    q = q.reshape(B, T, H, hd)
    k = k.reshape(B, T, Hkv, hd)
    v = v.reshape(B, T, Hkv, hd)
    if "q_norm" in lp["attn"] and not cfg.qk_norm_full:
        # qwen3/gemma3: head-wise RMSNorm BEFORE rope
        q = _qk_rmsnorm(q, lp["attn"]["q_norm"], cfg.norm_eps)
        k = _qk_rmsnorm(k, lp["attn"]["k_norm"], cfg.norm_eps)
    if cfg.pos_embedding == "rope":
        if cfg.local_rope_theta is not None and rope_local is not None:
            # gemma-3: SLIDING layers rotate with the local theta and no
            # scaling; global layers use rope_theta + rope_scaling.
            # rope_local is the (traced) is-sliding flag for this layer
            def rot2(v):
                g_ = _rope(v, positions, cfg.rope_theta, cfg.rotary_dim,
                           cfg.rope_style, cfg.rope_scaling)
                l_ = _rope(v, positions, cfg.local_rope_theta,
                           cfg.rotary_dim, cfg.rope_style, None)
                return jnp.where(rope_local, l_, g_)

            q, k = rot2(q), rot2(k)
        else:
            q = _rope(q, positions, cfg.rope_theta, cfg.rotary_dim,
                      cfg.rope_style, cfg.rope_scaling)
            k = _rope(k, positions, cfg.rope_theta, cfg.rotary_dim,
                      cfg.rope_style, cfg.rope_scaling)
    if kv_hook is not None:
        k, v = kv_hook(k, v)
    if attn_fn is None:
        attn_out = _attention(q, k, v, mask, cfg)
    else:
        attn_out = attn_fn(q, k, v, mask, cfg, positions=positions)
    attn_out = lora_matmul(attn_out, lp["attn"]["wo"], "wo", lora)
    if "bo" in lp["attn"]:
        attn_out = attn_out + lp["attn"]["bo"]
    if cfg.parallel_block:
        # parallel residual: attention and MLP branches sum into x. phi
        # (parallel_norms=1) feeds both from ln1's output; gpt-neox
        # (parallel_norms=2) norms the mlp branch separately with ln2
        h_mlp = h if cfg.parallel_norms == 1 else _norm(x, lp["ln2"], cfg)
        return x + attn_out + _mlp(h_mlp, lp["mlp"], cfg, lora)
    if cfg.post_norms:  # gemma-2/olmo2: norm the attn OUTPUT
        attn_out = _norm(attn_out, lp["ln1_post"], cfg)
    x = x + attn_out

    h2 = x if cfg.no_pre_norms else _norm(x, lp["ln2"], cfg)
    # MoE keeps base experts (lora MLP targets are rejected per-model by
    # train/lora.validate_targets — expert weights carry an [L, E, ...] dim)
    mlp_out = (
        _moe(h2, lp["moe"], cfg) if cfg.is_moe else _mlp(h2, lp["mlp"], cfg, lora)
    )
    if cfg.post_norms:
        mlp_out = _norm(mlp_out, lp["ln2_post"], cfg)
    return x + mlp_out


def final_logits(params: Params, cfg: ModelConfig, x):
    """Final norm + LM head (+softcap), f32 logits."""
    x = _norm(x, params["final_norm"], cfg)
    if cfg.tie_embeddings:
        logits = x @ params["tok_embed"].T
    else:
        logits = x @ params["lm_head"]
        if "lm_head_bias" in params:
            logits = logits + params["lm_head_bias"]
    logits = logits.astype(jnp.float32)
    if cfg.logits_softcap:
        c = cfg.logits_softcap
        logits = jnp.tanh(logits / c) * c
    return logits


# ---------------------------------------------------------------- forward


def attn_mask(cfg: ModelConfig, positions, T: int, S: int | None = None,
              window: int | None | str = "cfg"):
    """THE attention mask builder (sliding window included) — core.forward
    and stages.stage_forward must agree or a pipeline-split model diverges
    from the monolithic one.

    Cached (S given): [B, 1, T, S] over cache positions — s visible to
    query t iff s <= pos(t), and with a sliding window only the last W
    positions (s > pos(t) - W). Uncached: causal [1, 1, T, T] with the
    same window restriction. `window` overrides cfg.sliding_window
    (None = full causal) — the gemma-2 alternating pattern builds both
    variants from the same config."""
    w = cfg.sliding_window if window == "cfg" else window
    if S is not None:
        s_idx = jnp.arange(S, dtype=jnp.int32)[None, None, :]  # [1,1,S]
        q_pos = positions[:, :, None]  # [B,T,1]
        mask = s_idx <= q_pos  # [B,T,S]
        if w:
            mask = mask & (s_idx > q_pos - w)
        return mask[:, None, :, :]
    causal = jnp.tril(jnp.ones((T, T), bool))
    if w:
        qi = jnp.arange(T, dtype=jnp.int32)[:, None]
        ki = jnp.arange(T, dtype=jnp.int32)[None, :]
        causal = causal & (qi - ki < w)
    return causal[None, None, :, :]


def is_sliding_layer(cfg: ModelConfig, global_idx):
    """Traced bool: does the layer at GLOBAL index window? THE one
    implementation of the local/global layer pattern (gemma-2: residue 0
    mod 2; gemma-3: residues 0..4 mod 6)."""
    res = jnp.asarray(cfg.sliding_window_residues, jnp.int32)
    return jnp.any(res == (global_idx % cfg.sliding_window_every))


def make_layer_mask(cfg: ModelConfig, positions, T: int, S: int | None = None,
                    start: int = 0):
    """Per-layer mask selector — THE one implementation of the gemma-2/3
    local/global alternation, shared by core.forward (start=0) and
    stages.stage_forward (start=spec.start). Non-alternating configs get
    the single attn_mask back for every layer."""
    mask = attn_mask(cfg, positions, T, S)
    if not (cfg.sliding_window and cfg.sliding_window_every > 1):
        return lambda idx: mask
    mask_full = attn_mask(cfg, positions, T, S, window=None)
    return lambda idx: jnp.where(is_sliding_layer(cfg, start + idx),
                                 mask, mask_full)


def make_layer_window(cfg: ModelConfig):
    """Per-layer effective sliding window as a [1] int32 (0 = full
    causal) — the ragged paged kernel's compact replacement for the bool
    mask (ops/ragged.py derives causality and ragged lengths from the
    per-row offsets, so the window is the ONLY mask information it needs,
    and a 16-lane bool mask block would not tile on TPU anyway). The
    per-layer selection uses the SAME is_sliding_layer rule as
    make_layer_mask, so the gemma-2/3 local/global alternation is
    identical across the dense and ragged paths."""
    w = int(cfg.sliding_window or 0)
    if not (w and cfg.sliding_window_every > 1):
        const = jnp.full((1,), w, jnp.int32)
        return lambda idx: const
    return lambda idx: jnp.where(
        is_sliding_layer(cfg, idx), w, 0
    ).astype(jnp.int32).reshape(1)


def forward(
    params: Params,
    cfg: ModelConfig,
    input_ids,  # [B, T] int32
    cache,  # {"k": [L,B,S,Hkv,hd], "v": ...} or None (no-cache full forward)
    offset,  # [] or [B] int32: write position of input_ids[:, 0] in the cache
    remat: bool = False,  # jax.checkpoint each layer (training: HBM for FLOPs)
    attn_fn=None,  # custom attention (ops.flash / parallel.ring); None = dense
    block_tables=None,  # [B, MB] int32: paged cache — see below
    paged_write_floor=None,  # [] int32: drop paged WRITES below this position
    paged_write_ceil=None,  # [] int32: drop paged WRITES at/after this position
    adapters=None,  # multi-LoRA serving (adapters/pool.py): stacked pool
    # factors {target: {"a": [L, N, din, r], "b": [L, N, r, dout]}}
    adapter_ids=None,  # [B] int32: each row's pool slot (0 = no adapter)
    adapter_scales=None,  # [N] f32: per-slot alpha/rank scaling
):
    """Run a [B, T] token chunk. Returns (logits [B, T, V], new_cache).

    With a cache: K/V for this chunk are written at [offset, offset+T) and
    attention looks at cache positions < offset+T (causally within the
    chunk). Without a cache (cache=None): plain causal self-attention over
    the chunk — the training/scoring path.

    With ``block_tables`` [B, MB], the cache is a PAGED pool
    {"k","v"}: [L, Hkv, num_blocks, block_size, hd] (init_paged_pool) and
    row b's logical cache position p lives at pool slot
    (block_tables[b, p // block_size], p % block_size) of every kv head.
    Writes scatter the chunk into the mapped blocks. Attention depends on
    the attn_fn: a RAGGED attn_fn (ops/ragged.make_ragged_attn_fn, marked
    by its ``ragged`` attribute) reads the pool directly — the kv_hook
    hands the per-layer pool slices through untouched and the kernel
    gathers one block per grid step, so neither the [B, S, Hkv, hd] view
    nor the [T, S] scores ever materialize. The dense path (attn_fn None)
    gathers the MB mapped blocks per row into that view; either way cache
    traffic per step scales with the table width the caller passes (live
    blocks, bucketed) instead of the pool capacity. The position→slot map
    is order-preserving, so every mask (causal, sliding-window, gemma
    alternation) and the ALiBi bias apply unchanged over the gathered
    [B, MB*block_size] coordinate space — the ragged kernel consumes the
    SAME mask, blocked per page. Table entries past a row's live extent
    must map to blocks whose positions are causally masked (the engine
    pads with the reserved null block 0).

    ``paged_write_floor`` / ``paged_write_ceil`` (paged only): scatter
    writes outside [floor, ceil) are redirected to the null block — reads
    still see the existing pool content. The floor protects copy-on-write
    shares (the engine's chunked-prefill capacity re-anchor can re-feed
    tokens BELOW a share point, and recomputed K/V under a different
    chunk geometry is not guaranteed bit-identical, so shared donor
    blocks must stay read-only). The ceil drops a prefill bucket's padded
    tail, so a short prompt never needs pool blocks past
    ceil(prompt_len / block_size) — pad positions are causally masked and
    decode overwrites its own positions before reading them.

    **Quantized pool** (EngineConfig.cache_dtype="int8"): the pool dict
    additionally carries ``k_scale``/``v_scale`` [L, Hkv, NB] f32
    per-page-per-head scales (init_paged_pool). The paged scatter becomes
    quantize-on-write (_quantized_page_write: amax per (page, head) →
    int8 + running-max scale, requantizing a page whose scale grew), the
    ragged attn_fn receives (pool_slice, scale_slice) tuples and
    dequantizes INSIDE its page loop, and the dense/sp fallback
    dequantizes the gathered view — K/V never materialize wider than one
    block (kernel) or the existing gathered view (fallback) anywhere.
    The write-floor CoW argument carries over unchanged: redirected
    positions touch only the null block, so shared donor pages keep both
    their bytes AND their scales.
    """
    B, T = input_ids.shape

    off = jnp.asarray(offset, jnp.int32)
    off_b = jnp.broadcast_to(off.reshape(-1), (B,))  # [B]
    positions = off_b[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B, T]

    x = embed_tokens(params, cfg, input_ids, positions)

    if block_tables is not None:
        bt = jnp.asarray(block_tables, jnp.int32)
        BS = cache["k"].shape[3]  # pool block size
        S = bt.shape[1] * BS  # gathered view width = logical positions
        wfloor = (
            jnp.asarray(paged_write_floor, jnp.int32)
            if paged_write_floor is not None else None
        )
        wceil = (
            jnp.asarray(paged_write_ceil, jnp.int32)
            if paged_write_ceil is not None else None
        )
    else:
        bt = None
        S = cache["k"].shape[2] if cache is not None else None
    # int8 cache: scales must ride along or writes would silently
    # astype-truncate K/V into garbage bit patterns — and only the PAGED
    # pool implements quantize-on-write, so an int8 rectangular cache is
    # rejected outright (static trace-time check, not a traced branch)
    quantized = bt is not None and cache is not None and "k_scale" in cache
    if (
        cache is not None
        and cache["k"].dtype == jnp.int8
        and not quantized
    ):
        raise ValueError(
            "int8 KV cache requires the paged pool with its "
            "k_scale/v_scale scale arrays (init_paged_pool dtype=int8 "
            "+ block_tables); the rectangular cache has no quantized path"
        )
    # pool-direct attention: the ragged kernel gathers blocks itself, so
    # it needs the tables; kv_hook then skips the gathered-view build and
    # the per-layer "mask" becomes the compact window selector — nothing
    # S-wide is materialized on this path at all
    ragged = bt is not None and getattr(attn_fn, "ragged", False)
    if ragged:
        attn_fn = functools.partial(attn_fn, block_tables=bt)
        layer_mask = make_layer_window(cfg)
    else:
        layer_mask = make_layer_mask(cfg, positions, T, S)

    # multi-adapter serving: the per-row slot ids and scales are batch-
    # constant across layers; the stacked factors ride the layer loop
    # (scan xs / per-layer index) so one layer's [N, din, r] slice — not
    # the whole [L, ...] stack — enters each block's gather
    if adapters is not None:
        aids = jnp.asarray(adapter_ids, jnp.int32)
        ascale = jnp.asarray(adapter_scales, jnp.float32)

        def lora_for(lad):
            return {"ab": lad, "ids": aids, "scale": ascale}
    else:
        lora_for = None

    def rope_flag(layer_idx):
        if cfg.local_rope_theta is None:
            return None
        return is_sliding_layer(cfg, layer_idx)

    def layer(carry, xs):
        x, lcache = carry
        lp, layer_idx = xs[0], xs[1]
        lora = lora_for(xs[2]) if len(xs) > 2 else None

        if lcache is None:  # training/scoring path: plain block
            return (
                transformer_block(lp, cfg, x, positions,
                                  layer_mask(layer_idx), attn_fn=attn_fn,
                                  rope_local=rope_flag(layer_idx), lora=lora),
                None,
            ), None

        def kv_hook(k, v):
            # write this chunk's K/V at [offset, offset+T) per batch row,
            # then attend over the whole cache row
            nonlocal lcache

            if bt is not None:
                # paged: scatter each position into its mapped (block, slot)
                # of every kv head. Rows own disjoint blocks (the engine's
                # allocator invariant), so the scatter indices never
                # collide across rows except in the garbage null block 0.
                Hkv, hd = k.shape[-2], k.shape[-1]
                blk = jnp.take_along_axis(bt, positions // BS, axis=1)
                slot = positions % BS  # [B, T]
                if wfloor is not None:
                    # re-fed positions below the share point write to the
                    # null block instead — shared donor blocks stay
                    # read-only (their content is already correct)
                    blk = jnp.where(positions >= wfloor, blk, 0)
                if wceil is not None:
                    # the bucket's padded tail writes to the null block —
                    # short prompts never claim blocks past their length
                    # (an out-of-table lookup above may have produced a
                    # fill value; this rewrites it to the real null block)
                    blk = jnp.where(positions < wceil, blk, 0)
                # pool layer [Hkv, NB, BS, hd]: the leading slice before
                # the (blk, slot) index arrays keeps the head dim in
                # place, so the update operand is k as [Hkv, B, T, hd]
                kT = jnp.transpose(k, (2, 0, 1, 3))
                vT = jnp.transpose(v, (2, 0, 1, 3))
                if quantized:
                    # chunk-position → page-window slot for the touched-
                    # page dedup (positions[:, 0] == off_b)
                    wslot = positions // BS - (off_b // BS)[:, None]
                    ck, ks = _quantized_page_write(
                        lcache["k"][layer_idx],
                        lcache["k_scale"][layer_idx], blk, slot, wslot, kT,
                    )
                    cv, vs = _quantized_page_write(
                        lcache["v"][layer_idx],
                        lcache["v_scale"][layer_idx], blk, slot, wslot, vT,
                    )
                    lcache = dict(
                        lcache,
                        k=lcache["k"].at[layer_idx].set(ck),
                        v=lcache["v"].at[layer_idx].set(cv),
                        k_scale=lcache["k_scale"].at[layer_idx].set(ks),
                        v_scale=lcache["v_scale"].at[layer_idx].set(vs),
                    )
                    if ragged:
                        # (pool slice, scale slice): the kernel dequants
                        # inside its page loop — int8 is all that crosses
                        # HBM, one block's dequant lives in VMEM
                        return (ck, ks), (cv, vs)
                    # dense/sp fallback: dequantize the gathered view —
                    # the same [B, S, Hkv, hd] width the bf16 path builds
                    k_eff = jnp.transpose(
                        ck[:, bt].astype(jnp.float32)
                        * ks[:, bt][..., None, None],
                        (1, 2, 3, 0, 4),
                    ).reshape(B, S, Hkv, hd).astype(k.dtype)
                    v_eff = jnp.transpose(
                        cv[:, bt].astype(jnp.float32)
                        * vs[:, bt][..., None, None],
                        (1, 2, 3, 0, 4),
                    ).reshape(B, S, Hkv, hd).astype(v.dtype)
                    return k_eff, v_eff
                ck = lcache["k"][layer_idx].at[:, blk, slot].set(
                    kT.astype(lcache["k"].dtype)
                )
                cv = lcache["v"][layer_idx].at[:, blk, slot].set(
                    vT.astype(lcache["v"].dtype)
                )
                lcache = dict(
                    lcache,
                    k=lcache["k"].at[layer_idx].set(ck),
                    v=lcache["v"].at[layer_idx].set(cv),
                )
                if ragged:
                    # the kernel gathers straight from the pool — no
                    # [B, S, Hkv, hd] view, no [T, S] scores
                    return ck, cv
                k_eff = jnp.transpose(ck[:, bt], (1, 2, 3, 0, 4)).reshape(
                    B, S, Hkv, hd
                )
                v_eff = jnp.transpose(cv[:, bt], (1, 2, 3, 0, 4)).reshape(
                    B, S, Hkv, hd
                )
                return k_eff, v_eff

            def write(cache_row, new_row, start):
                return lax.dynamic_update_slice(
                    cache_row, new_row.astype(cache_row.dtype), (start, 0, 0)
                )

            ck = jax.vmap(write)(lcache["k"][layer_idx], k, off_b)
            cv = jax.vmap(write)(lcache["v"][layer_idx], v, off_b)
            lcache = dict(
                lcache,
                k=lcache["k"].at[layer_idx].set(ck),
                v=lcache["v"].at[layer_idx].set(cv),
            )
            return ck, cv

        x = transformer_block(
            lp, cfg, x, positions, layer_mask(layer_idx),
            kv_hook=kv_hook, attn_fn=attn_fn,
            rope_local=rope_flag(layer_idx), lora=lora,
        )
        return (x, lcache), None

    layer_params = params["layers"]
    n_layers = cfg.n_layers
    # prevent_cse=False: checkpoint inside lax.scan doesn't need the CSE
    # barrier (scan's loop structure already prevents it) and the barrier
    # blocks XLA fusion otherwise
    layer_body = jax.checkpoint(layer, prevent_cse=False) if remat else layer
    if isinstance(layer_params, (list, tuple)):
        # Unstacked layers (list of per-layer trees): unrolled loop. This
        # is the CPU serving fast path — XLA:CPU cannot pre-pack a GEMM
        # operand it first has to slice out of the stacked [L, ...] array,
        # so every dot inside scan falls off the packed-GEMM path
        # (measured: 24 ms vs 1.1 ms per distilgpt2 block at T=1).
        # Per-layer arrays arrive as separate, contiguous jit arguments
        # and GEMM packing works. TPU keeps the stacked scan below
        # (compile-time scales O(1) in depth; Mosaic handles layouts).
        # models.unstack_layers converts; engine does it when backend=cpu.
        carry = (x, cache)
        for i, lp in enumerate(layer_params):
            if adapters is not None:
                lad = jax.tree.map(lambda a: a[i], adapters)
                carry, _ = layer_body(carry, (lp, i, lad))
            else:
                carry, _ = layer_body(carry, (lp, i))
        x, new_cache = carry
    else:
        xs = (layer_params, jnp.arange(n_layers))
        if adapters is not None:
            # the [L, N, ...] factor stacks join the scan xs, so each
            # layer body sees only its own [N, ...] slice; adapters=None
            # keeps the 2-tuple — the pre-adapter trace is unchanged
            xs = xs + (adapters,)
        (x, new_cache), _ = lax.scan(layer_body, (x, cache), xs)

    return final_logits(params, cfg, x), new_cache


def unstack_layers(params: Params) -> Params:
    """Convert stacked [L, ...] layer params into a list of per-layer
    contiguous trees (forward()'s unrolled path). Host-side numpy copies
    so each weight is its own packed buffer — the whole point is giving
    XLA:CPU pre-packable GEMM operands; quantized {"q","s"} subtrees pass
    through like any other leaves."""
    import numpy as np

    stacked = params["layers"]
    if isinstance(stacked, (list, tuple)):
        return params  # already unstacked: slicing again would shred weights
    n = len(jax.tree.leaves(stacked)[0])
    out = dict(params)
    out["layers"] = [
        jax.tree.map(lambda a: np.ascontiguousarray(np.asarray(a[i])), stacked)
        for i in range(n)
    ]
    return out


def restack_layers(params: Params) -> Params:
    """Inverse of unstack_layers: list of per-layer trees → stacked
    [L, ...] arrays. Consumers that serialize or shard the canonical
    layout (weight publishing, export) restack a CPU engine's params
    before use — np.asarray on the list would silently produce a
    dtype=object array of POINTERS, not weights."""
    import numpy as np

    layers = params["layers"]
    if not isinstance(layers, (list, tuple)):
        return params
    out = dict(params)
    out["layers"] = jax.tree.map(
        lambda *leaves: np.stack([np.asarray(a) for a in leaves]), *layers
    )
    return out


def init_cache(cfg: ModelConfig, batch: int, max_len: int | None = None, dtype=jnp.bfloat16):
    """Preallocate a fixed-capacity KV cache: {"k","v"}: [L,B,S,Hkv,hd].

    Model-level utility for forward()'s contiguous cache path (per-stage
    pipeline caches, scoring/offline use). The SERVING engine no longer
    allocates these — its one cache layout is the paged block pool
    (init_paged_pool; engine/scheduler.py)."""
    S = max_len or cfg.max_seq_len
    shape = (cfg.n_layers, batch, S, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def init_paged_pool(
    cfg: ModelConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16
):
    """Preallocate the paged KV block pool:
    {"k","v"}: [L, Hkv, num_blocks, block_size, hd]. Block 0 is the
    engine's reserved null block (padding target for table entries past a
    row's live extent); rows map logical positions onto blocks via the
    block tables forward() takes.

    Head-major layout: the ragged kernel (ops/ragged.py) gathers one
    (kv_head, block) tile per grid step, and Mosaic needs the trailing
    two dims of that tile to be (block_size, hd) — a head axis blocked
    at 1 in trailing position fails to lower, the same constraint that
    shaped ops/flash.py's head-major transpose.

    With ``dtype=int8`` (EngineConfig.cache_dtype="int8") the pool pages
    store quantized K/V and the dict grows ``k_scale``/``v_scale``
    [L, Hkv, num_blocks] f32 per-page-per-head symmetric scales —
    initialized to ZERO (= "page holds nothing"; forward's running-max
    quantize-on-write takes it from there, and the scheduler re-zeroes a
    block's entry when the allocator recycles it). Pool HBM halves vs
    bf16 at a 4 / (block_size * head_dim) scale overhead (~0.4% at the
    16x64 default)."""
    shape = (cfg.n_layers, cfg.n_kv_heads, num_blocks, block_size, cfg.head_dim)
    pool = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if jnp.dtype(dtype) == jnp.int8:
        sshape = (cfg.n_layers, cfg.n_kv_heads, num_blocks)
        pool["k_scale"] = jnp.zeros(sshape, jnp.float32)
        pool["v_scale"] = jnp.zeros(sshape, jnp.float32)
    return pool
