"""Partition rules: param path → PartitionSpec over the named mesh axes.

This is the TP/EP layout for the BASELINE ladder (Llama-3-8B TP on v5e-8,
Mixtral EP on v5e-16). Megatron-style column/row split per block:

- wq/wk/wv: columns (head dim) on `model` → attention heads are sharded,
  no collective inside attention
- wo: rows on `model` → XLA inserts one psum (all-reduce) per layer
- w_up/w_gate: columns on `model`; w_down: rows on `model` → one psum
- tok_embed: vocab dim on `model` (all-gather of the embedding row);
  lm_head: vocab columns on `model` (logits computed sharded)
- MoE experts: E dim on `expert` axis; router replicated
- KV cache: kv-head dim on `model` (decode-time attention stays local)

All specs are expressed over param PATHS (tuple of pytree keys), so the
same rules drive (a) NamedSharding for jit, (b) the piece/shard manifest
(pieces.build_shard_manifest), and (c) checkpoint resharding.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import ModelConfig

# rules: suffix of the "/"-joined param path → PartitionSpec
# (leading L dim on layer-stacked params is never sharded → spec starts None)
_RULES: list[tuple[str, P]] = [
    ("tok_embed", P("model", None)),
    ("pos_embed", P(None, None)),
    ("lm_head", P(None, "model")),
    ("final_norm/scale", P(None)),
    ("final_norm/bias", P(None)),
    # attention (layer-stacked: [L, ...])
    ("attn/wq", P(None, None, "model")),
    ("attn/wk", P(None, None, "model")),
    ("attn/wv", P(None, None, "model")),
    ("attn/wo", P(None, "model", None)),
    ("attn/bq", P(None, "model")),
    ("attn/bk", P(None, "model")),
    ("attn/bv", P(None, "model")),
    ("attn/bo", P(None, None)),
    # dense mlp
    ("mlp/w_up", P(None, None, "model")),
    ("mlp/w_gate", P(None, None, "model")),
    ("mlp/w_down", P(None, "model", None)),
    ("mlp/b_up", P(None, "model")),
    ("mlp/b_down", P(None, None)),
    # moe: experts on `expert`, inner dims on `model`
    ("moe/router", P(None, None, None)),
    ("moe/w_up", P(None, "expert", None, "model")),
    ("moe/w_gate", P(None, "expert", None, "model")),
    ("moe/w_down", P(None, "expert", "model", None)),
    # norms
    ("ln1/scale", P(None, None)),
    ("ln1/bias", P(None, None)),
    ("ln2/scale", P(None, None)),
    ("ln2/bias", P(None, None)),
]


def _unquant_path(path: str) -> tuple[str, str | None]:
    """Strip a quantization leaf suffix: "attn/wq/q" -> ("attn/wq", "q").
    models/quant.py stores int8 weights as {"q","s"} subtrees; partition
    rules are written against the WEIGHT path."""
    if path.endswith(("/q", "/s")):
        return path[:-2], path[-1]
    return path, None


def spec_for_path(path: str) -> P:
    path, leaf = _unquant_path(path)
    for suffix, spec in _RULES:
        if path.endswith(suffix):
            if leaf == "s":
                # per-out-channel scales are the weight minus its IN axis
                # (dim -2): [L, out] for dense weights, [L, E, out] for MoE
                # experts — shard like the surviving axes of the weight
                return P(*spec[:-2], spec[-1])
            return spec
    return P()  # replicate by default


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


def partition_specs(params) -> dict:
    """Pytree of PartitionSpec matching `params`' structure."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: spec_for_path(_path_str(path)), params
    )


def _fits(leaf, spec: P, mesh: Mesh) -> bool:
    for dim, entry in zip(leaf.shape, spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        n = 1
        for a in axes:
            n *= mesh.shape.get(a, 1)
        if dim % n:
            return False
    return True


def kv_replicated(cfg: ModelConfig, mesh: Mesh) -> bool:
    """True when K/V heads must be replicated across the `model` axis:
    MQA/GQA with tp > n_kv_heads (e.g. gemma-2b's single kv head on a
    model=4 mesh). A width split of wk/wv would cut one kv head's hd dim
    across devices and break per-shard attention locality; whole-head
    replication keeps attention collective-free at the cost of duplicate
    K/V compute (tiny: Hkv=1 projections are ~1/(H) of attention width)."""
    tp = mesh.shape.get("model", 1)
    return tp > 1 and cfg.n_kv_heads % tp != 0


_KV_PARAM_SUFFIXES = ("attn/wk", "attn/wv", "attn/bk", "attn/bv")


def shard_params(params, mesh: Mesh, cfg: ModelConfig | None = None):
    """Place params onto the mesh per the rules (host → device transfer).
    Params whose sharded dim doesn't divide the mesh axis (e.g. gpt2's prime
    vocab on tok_embed/lm_head) are replicated instead. With `cfg` given,
    MQA models replicate the K/V projections (see kv_replicated)."""
    specs = partition_specs(params)
    if cfg is not None and kv_replicated(cfg, mesh):
        specs = jax.tree_util.tree_map_with_path(
            lambda path, s: (
                P()
                if _unquant_path(_path_str(path))[0].endswith(_KV_PARAM_SUFFIXES)
                else s
            ),
            specs,
        )
    def place(leaf, spec):
        t = tuple(spec)
        if len(t) > getattr(leaf, "ndim", 0):
            # rules are written against STACKED [L, ...] weights; unstacked
            # per-layer leaves (core.unstack_layers, the CPU path) drop the
            # leading layer dim — trim leading spec entries to match. Only
            # None entries may be dropped: trimming a real mesh axis would
            # silently mask a rule/shape mismatch that must fail loudly.
            drop, t = t[: len(t) - leaf.ndim], t[len(t) - leaf.ndim:]
            if any(d is not None for d in drop):
                raise ValueError(
                    f"partition spec {spec} does not fit rank-{leaf.ndim} "
                    f"leaf: would drop sharded axes {drop}"
                )
        spec = P(*t)
        return jax.device_put(
            leaf, NamedSharding(mesh, spec if _fits(leaf, spec, mesh) else P())
        )

    return jax.tree.map(place, params, specs)


def cache_spec(
    cfg: ModelConfig | None = None,
    mesh: Mesh | None = None,
    seq_sharded: bool = False,
) -> P:
    """KV cache [L, B, S, Hkv, hd]: batch on `data`, kv heads on `model`.

    With ``seq_sharded=True`` (the engine sets it iff attention='sp'),
    cache capacity S is sharded over `seq`: per-device cache memory is
    S/seq and long contexts scale with devices (parallel/sp_serving.py).
    It is NOT inferred from the mesh alone — dense/flash attention gathers
    the full cache per step, so a seq-sharded cache under them would be a
    silent per-step reshard, not a win. MQA meshes (kv_replicated) keep
    the kv-head dim replicated to match the replicated wk/wv projections."""
    seq = "seq" if seq_sharded and mesh is not None and mesh.shape.get("seq", 1) > 1 else None
    if cfg is not None and mesh is not None and kv_replicated(cfg, mesh):
        return P(None, "data", seq, None, None)
    return P(None, "data", seq, "model", None)


def paged_cache_spec(
    cfg: ModelConfig | None = None,
    mesh: Mesh | None = None,
    seq_sharded: bool = False,
) -> P:
    """Paged KV pool [L, Hkv, num_blocks, block_size, hd]: kv heads on
    `model` — attention over the pool (ragged kernel) or its gathered
    view (dense) stays collective-free per shard. The BLOCK dim is never
    sharded: any row gathers arbitrary pool blocks, so splitting it would
    turn every gather into a cross-device reshard. With ``seq_sharded``
    (the engine sets it iff attention='sp') the SLOT dim shards over
    `seq`: per-device pool memory is 1/seq — the long-context capacity
    scaling of parallel/sp_serving — and the block gather stays local
    (it indexes only the block dim); XLA reshards the gathered view into
    the sp shard_map's contiguous [B, S/seq] layout per step, which is
    the collective sp attention pays anyway. MQA meshes (kv_replicated)
    replicate the kv-head dim to match wk/wv."""
    seq = "seq" if seq_sharded and mesh is not None and mesh.shape.get("seq", 1) > 1 else None
    if cfg is not None and mesh is not None and kv_replicated(cfg, mesh):
        return P(None, None, None, seq, None)
    return P(None, "model", None, seq, None)


def paged_scale_spec(cfg: ModelConfig | None = None, mesh: Mesh | None = None) -> P:
    """Int8-pool quantization scales [L, Hkv, num_blocks] f32: the
    kv-head dim shards exactly like the pool's (MQA replication
    included), the block dim never shards (same any-row-any-block
    argument as paged_cache_spec), and there is no slot dim — under
    attention='sp' the scales stay whole per shard and the gathered-view
    dequant broadcasts each page's scale across its (seq-sharded) slots
    locally."""
    if cfg is not None and mesh is not None and kv_replicated(cfg, mesh):
        return P(None, None, None)
    return P(None, "model", None)


def flat_partition_specs(
    params,
    mesh_axes: dict[str, int] | None = None,
    cfg: ModelConfig | None = None,
) -> dict[str, tuple]:
    """{path_str: spec-as-tuple} for pieces.build_shard_manifest, which
    wants mesh-axis names per tensor axis. With `mesh_axes` given, specs
    whose dims don't divide the axis size degrade to replicated — mirroring
    shard_params' fallback. With `cfg` given, the MQA K/V replication
    override matches shard_params too, keeping the manifest<->jit-sharding
    invariant (a peer's assembled pieces must equal its jit shard)."""
    out = {}
    tp = (mesh_axes or {}).get("model", 1)
    kv_repl = cfg is not None and tp > 1 and cfg.n_kv_heads % tp != 0

    def visit(path, leaf):
        ps = _path_str(path)
        spec = tuple(spec_for_path(ps))
        if kv_repl and _unquant_path(ps)[0].endswith(_KV_PARAM_SUFFIXES):
            spec = ()
        if mesh_axes:
            ok = all(
                e is None or leaf.shape[i] % mesh_axes.get(e, 1) == 0
                for i, e in enumerate(spec)
            )
            if not ok:
                spec = ()
        out[ps] = spec
        return leaf

    jax.tree_util.tree_map_with_path(visit, params)
    return out


def validate_divisibility(cfg: ModelConfig, mesh: Mesh) -> None:
    """Fail fast when the model's dims don't divide the mesh axes."""
    tp = mesh.shape.get("model", 1)
    ep = mesh.shape.get("expert", 1)
    problems = []
    # n_kv_heads % tp != 0 is NOT fatal: kv_replicated() keeps K/V whole
    # per shard (MQA replication), so gemma-2b (Hkv=1) serves at model=4
    if (cfg.n_heads * cfg.head_dim) % tp:
        problems.append(f"attn width {cfg.n_heads * cfg.head_dim} vs model axis {tp}")
    if cfg.d_ff % tp:
        problems.append(f"d_ff={cfg.d_ff} vs model axis {tp}")
    # note: vocab (tok_embed/lm_head) indivisibility is NOT fatal —
    # shard_params falls back to replicating those params (gpt2's 50257
    # vocab is prime, yet gpt2 must still run TP on its other dims)
    if cfg.is_moe and cfg.n_experts % ep:
        problems.append(f"n_experts={cfg.n_experts} vs expert axis {ep}")
    if problems:
        raise ValueError(f"model {cfg.name} does not fit mesh {dict(mesh.shape)}: " + "; ".join(problems))
