"""Checkpoint loading: local HF checkpoints → our param layout, plus a
native orbax format for checkpoint/resume (a capability the reference lacks
entirely — SURVEY §5 "Checkpoint/resume: none").

HF weight name mapping covers the GPT-2 and Llama/Mistral/Mixtral/Gemma
families (the reference loads these via transformers at hf.py:23-32; we map
tensor names directly so torch is never needed on the serving path —
safetensors files are read with numpy). Everything is offline: paths must
exist locally; nothing downloads.
"""

from __future__ import annotations

import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig
from .core import init_params


def _stack(arrs):
    return np.stack(arrs, axis=0)


def _read_safetensors(path: Path) -> dict[str, np.ndarray]:
    """Minimal safetensors reader (header JSON + raw buffers); avoids a torch
    dependency on the serving path."""
    out = {}
    dtype_map = {
        "F32": np.float32, "F16": np.float16,
        "I64": np.int64, "I32": np.int32, "U8": np.uint8, "BOOL": np.bool_,
    }
    # seek+read per tensor: peak host memory stays one-tensor-sized, not
    # whole-shard-sized (llama shards are ~5 GB each)
    with open(path, "rb") as f:
        n = int.from_bytes(f.read(8), "little")
        header = json.loads(f.read(n).decode("utf-8"))
        base = 8 + n
        for name, spec in header.items():
            if name == "__metadata__":
                continue
            start, end = spec["data_offsets"]
            f.seek(base + start)
            buf = f.read(end - start)
            if spec["dtype"] == "BF16":
                # widen bf16 via the uint16 bit pattern, independent of
                # whether this numpy has a native bfloat16
                raw_u16 = np.frombuffer(buf, np.uint16).reshape(spec["shape"])
                arr = (raw_u16.astype(np.uint32) << 16).view(np.float32)
            else:
                arr = np.frombuffer(buf, dtype_map[spec["dtype"]]).reshape(spec["shape"])
            out[name] = arr
    return out


def _load_hf_state(path: Path) -> dict[str, np.ndarray]:
    state: dict[str, np.ndarray] = {}
    st_files = sorted(path.glob("*.safetensors"))
    if st_files:
        for f in st_files:
            state.update(_read_safetensors(f))
        return state
    bins = sorted(path.glob("pytorch_model*.bin"))
    if bins:
        import torch  # cpu torch is available in this image

        for f in bins:
            sd = torch.load(f, map_location="cpu", weights_only=True)
            state.update({k: v.float().numpy() for k, v in sd.items()})
        return state
    raise FileNotFoundError(f"no safetensors or pytorch_model.bin under {path}")


def _convert_gpt2(state, cfg: ModelConfig) -> dict:
    """HF GPT-2 names → our layout. HF conv1d stores [in, out] already."""
    pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""
    g = lambda k: state[pre + k]
    L = cfg.n_layers
    layers = {
        "ln1": {"scale": _stack([g(f"h.{i}.ln_1.weight") for i in range(L)]),
                "bias": _stack([g(f"h.{i}.ln_1.bias") for i in range(L)])},
        "ln2": {"scale": _stack([g(f"h.{i}.ln_2.weight") for i in range(L)]),
                "bias": _stack([g(f"h.{i}.ln_2.bias") for i in range(L)])},
    }
    D = cfg.d_model
    qw, kw, vw, qb, kb, vb = [], [], [], [], [], []
    for i in range(L):
        w = g(f"h.{i}.attn.c_attn.weight")  # [D, 3D]
        b = g(f"h.{i}.attn.c_attn.bias")
        qw.append(w[:, :D]); kw.append(w[:, D:2 * D]); vw.append(w[:, 2 * D:])
        qb.append(b[:D]); kb.append(b[D:2 * D]); vb.append(b[2 * D:])
    layers["attn"] = {
        "wq": _stack(qw), "wk": _stack(kw), "wv": _stack(vw),
        "bq": _stack(qb), "bk": _stack(kb), "bv": _stack(vb),
        "wo": _stack([g(f"h.{i}.attn.c_proj.weight") for i in range(L)]),
        "bo": _stack([g(f"h.{i}.attn.c_proj.bias") for i in range(L)]),
    }
    layers["mlp"] = {
        "w_up": _stack([g(f"h.{i}.mlp.c_fc.weight") for i in range(L)]),
        "b_up": _stack([g(f"h.{i}.mlp.c_fc.bias") for i in range(L)]),
        "w_down": _stack([g(f"h.{i}.mlp.c_proj.weight") for i in range(L)]),
        "b_down": _stack([g(f"h.{i}.mlp.c_proj.bias") for i in range(L)]),
    }
    return {
        "tok_embed": g("wte.weight"),
        "pos_embed": g("wpe.weight"),
        "layers": layers,
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }


def _convert_bigcode(state, cfg: ModelConfig) -> dict:
    """HF GPT-BigCode (starcoder/santacoder) names → our layout. Same
    names as gpt2 but nn.Linear ([out, in]) instead of Conv1D, and the
    fused c_attn packs [D + 2*kv_dim] on the OUT dim: all query heads,
    then k, then v (MQA: kv_dim = head_dim)."""
    pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""
    g = lambda k: state[pre + k]
    t = lambda a: np.ascontiguousarray(a.T)
    L, D = cfg.n_layers, cfg.d_model
    kv = cfg.n_kv_heads * cfg.head_dim
    H, hd = cfg.n_heads, cfg.head_dim
    qw, kw, vw, qb, kb, vb = [], [], [], [], [], []
    for i in range(L):
        w = g(f"h.{i}.attn.c_attn.weight")  # [D + 2*kv, D]
        b = g(f"h.{i}.attn.c_attn.bias")
        if cfg.n_kv_heads == H:
            # multi_query=False packs q/k/v PER HEAD ([H, 3*hd] out-dims,
            # HF view(num_heads, 3*head_dim).split) — a sequential-thirds
            # split would scramble K/V across heads
            wr = w.reshape(H, 3, hd, D)
            br = b.reshape(H, 3, hd)
            for dst, bst, j in ((qw, qb, 0), (kw, kb, 1), (vw, vb, 2)):
                dst.append(np.ascontiguousarray(wr[:, j].reshape(H * hd, D).T))
                bst.append(np.ascontiguousarray(br[:, j].reshape(H * hd)))
        else:  # multi_query: query block, then one k head, then one v head
            qw.append(t(w[:D])); kw.append(t(w[D:D + kv])); vw.append(t(w[D + kv:]))
            qb.append(b[:D]); kb.append(b[D:D + kv]); vb.append(b[D + kv:])
    layers = {
        "ln1": {"scale": _stack([g(f"h.{i}.ln_1.weight") for i in range(L)]),
                "bias": _stack([g(f"h.{i}.ln_1.bias") for i in range(L)])},
        "ln2": {"scale": _stack([g(f"h.{i}.ln_2.weight") for i in range(L)]),
                "bias": _stack([g(f"h.{i}.ln_2.bias") for i in range(L)])},
        "attn": {
            "wq": _stack(qw), "wk": _stack(kw), "wv": _stack(vw),
            "bq": _stack(qb), "bk": _stack(kb), "bv": _stack(vb),
            "wo": _stack([t(g(f"h.{i}.attn.c_proj.weight")) for i in range(L)]),
            "bo": _stack([g(f"h.{i}.attn.c_proj.bias") for i in range(L)]),
        },
        "mlp": {
            "w_up": _stack([t(g(f"h.{i}.mlp.c_fc.weight")) for i in range(L)]),
            "b_up": _stack([g(f"h.{i}.mlp.c_fc.bias") for i in range(L)]),
            "w_down": _stack([t(g(f"h.{i}.mlp.c_proj.weight")) for i in range(L)]),
            "b_down": _stack([g(f"h.{i}.mlp.c_proj.bias") for i in range(L)]),
        },
    }
    out = {
        "tok_embed": g("wte.weight"),
        "pos_embed": g("wpe.weight"),
        "layers": layers,
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    if not cfg.tie_embeddings:
        lm = state.get("lm_head.weight")
        out["lm_head"] = (
            t(lm) if lm is not None
            else np.ascontiguousarray(g("wte.weight").T)
        )
    return out


def _convert_phi(state, cfg: ModelConfig) -> dict:
    """HF phi-2 names → our layout (microsoft/phi-2: parallel blocks with
    one input_layernorm, q/k/v/dense + fc1/fc2 all biased, untied
    lm_head with bias, final_layernorm). HF linear is [out, in] → ours
    [in, out]."""
    pre = "model." if any(k.startswith("model.") for k in state) else ""
    g = lambda k: state[pre + k]
    t = lambda a: np.ascontiguousarray(a.T)
    L = cfg.n_layers
    layers = {
        "ln1": {
            "scale": _stack([g(f"layers.{i}.input_layernorm.weight") for i in range(L)]),
            "bias": _stack([g(f"layers.{i}.input_layernorm.bias") for i in range(L)]),
        },
        "attn": {
            "wq": _stack([t(g(f"layers.{i}.self_attn.q_proj.weight")) for i in range(L)]),
            "wk": _stack([t(g(f"layers.{i}.self_attn.k_proj.weight")) for i in range(L)]),
            "wv": _stack([t(g(f"layers.{i}.self_attn.v_proj.weight")) for i in range(L)]),
            "wo": _stack([t(g(f"layers.{i}.self_attn.dense.weight")) for i in range(L)]),
            "bq": _stack([g(f"layers.{i}.self_attn.q_proj.bias") for i in range(L)]),
            "bk": _stack([g(f"layers.{i}.self_attn.k_proj.bias") for i in range(L)]),
            "bv": _stack([g(f"layers.{i}.self_attn.v_proj.bias") for i in range(L)]),
            "bo": _stack([g(f"layers.{i}.self_attn.dense.bias") for i in range(L)]),
        },
        "mlp": {
            "w_up": _stack([t(g(f"layers.{i}.mlp.fc1.weight")) for i in range(L)]),
            "b_up": _stack([g(f"layers.{i}.mlp.fc1.bias") for i in range(L)]),
            "w_down": _stack([t(g(f"layers.{i}.mlp.fc2.weight")) for i in range(L)]),
            "b_down": _stack([g(f"layers.{i}.mlp.fc2.bias") for i in range(L)]),
        },
    }
    return {
        "tok_embed": g("embed_tokens.weight"),
        "layers": layers,
        "final_norm": {
            "scale": g("final_layernorm.weight"),
            "bias": g("final_layernorm.bias"),
        },
        "lm_head": t(state["lm_head.weight"]),
        "lm_head_bias": state["lm_head.bias"],
    }


def _convert_gptj(state, cfg: ModelConfig) -> dict:
    """HF GPT-J names → our layout (transformer.h.N.{ln_1, attn.{q,k,v,
    out}_proj bias-free, mlp.{fc_in,fc_out} biased}, untied lm_head WITH
    bias). HF linear is [out, in] → ours [in, out]."""
    pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""
    g = lambda k: state[pre + k]
    t = lambda a: np.ascontiguousarray(a.T)
    L = cfg.n_layers
    layers = {
        "ln1": {
            "scale": _stack([g(f"h.{i}.ln_1.weight") for i in range(L)]),
            "bias": _stack([g(f"h.{i}.ln_1.bias") for i in range(L)]),
        },
        "attn": {
            "wq": _stack([t(g(f"h.{i}.attn.q_proj.weight")) for i in range(L)]),
            "wk": _stack([t(g(f"h.{i}.attn.k_proj.weight")) for i in range(L)]),
            "wv": _stack([t(g(f"h.{i}.attn.v_proj.weight")) for i in range(L)]),
            "wo": _stack([t(g(f"h.{i}.attn.out_proj.weight")) for i in range(L)]),
        },
        "mlp": {
            "w_up": _stack([t(g(f"h.{i}.mlp.fc_in.weight")) for i in range(L)]),
            "b_up": _stack([g(f"h.{i}.mlp.fc_in.bias") for i in range(L)]),
            "w_down": _stack([t(g(f"h.{i}.mlp.fc_out.weight")) for i in range(L)]),
            "b_down": _stack([g(f"h.{i}.mlp.fc_out.bias") for i in range(L)]),
        },
    }
    return {
        "tok_embed": g("wte.weight"),
        "layers": layers,
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
        "lm_head": t(state["lm_head.weight"]),
        "lm_head_bias": state["lm_head.bias"],
    }


def _convert_mpt(state, cfg: ModelConfig) -> dict:
    """HF MPT names → our layout: transformer.blocks.N.{norm_1, attn.Wqkv
    (sequential q|k|v thirds), attn.out_proj, norm_2, ffn.{up,down}_proj},
    weight-only norms, zero biases, tied head, ALiBi."""
    pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""
    g = lambda k: state[pre + k]
    t = lambda a: np.ascontiguousarray(a.T)
    L, D = cfg.n_layers, cfg.d_model
    qw, kw, vw = [], [], []
    for i in range(L):
        w = g(f"blocks.{i}.attn.Wqkv.weight")  # [3D, D], plain thirds
        qw.append(t(w[:D])); kw.append(t(w[D:2 * D])); vw.append(t(w[2 * D:]))
    layers = {
        "ln1": {"scale": _stack([g(f"blocks.{i}.norm_1.weight") for i in range(L)])},
        "ln2": {"scale": _stack([g(f"blocks.{i}.norm_2.weight") for i in range(L)])},
        "attn": {
            "wq": _stack(qw), "wk": _stack(kw), "wv": _stack(vw),
            "wo": _stack([t(g(f"blocks.{i}.attn.out_proj.weight")) for i in range(L)]),
        },
        "mlp": {
            "w_up": _stack([t(g(f"blocks.{i}.ffn.up_proj.weight")) for i in range(L)]),
            "w_down": _stack([t(g(f"blocks.{i}.ffn.down_proj.weight")) for i in range(L)]),
        },
    }
    out = {
        "tok_embed": g("wte.weight"),
        "layers": layers,
        "final_norm": {"scale": g("norm_f.weight")},
    }
    if not cfg.tie_embeddings:
        lm = state.get("lm_head.weight")
        out["lm_head"] = (
            t(lm) if lm is not None
            else np.ascontiguousarray(g("wte.weight").T)
        )
    return out


def _convert_bloom(state, cfg: ModelConfig) -> dict:
    """HF BLOOM names → our layout: word_embeddings + its LayerNorm,
    per-head [H, 3, hd] interleaved fused QKV WITH biases (same packing
    as gpt-neox), biased dense/mlp, sequential pre-norm blocks, ALiBi
    (no positional tensors at all)."""
    pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""
    g = lambda k: state[pre + k]
    t = lambda a: np.ascontiguousarray(a.T)
    L, D = cfg.n_layers, cfg.d_model
    H, hd = cfg.n_heads, cfg.head_dim
    qw, kw, vw, qb, kb, vb = [], [], [], [], [], []
    for i in range(L):
        w = g(f"h.{i}.self_attention.query_key_value.weight")  # [3D, D]
        b = g(f"h.{i}.self_attention.query_key_value.bias")
        wr = w.reshape(H, 3, hd, D)
        br = b.reshape(H, 3, hd)
        for dst, bst, j in ((qw, qb, 0), (kw, kb, 1), (vw, vb, 2)):
            dst.append(np.ascontiguousarray(wr[:, j].reshape(H * hd, D).T))
            bst.append(np.ascontiguousarray(br[:, j].reshape(H * hd)))
    layers = {
        "ln1": {
            "scale": _stack([g(f"h.{i}.input_layernorm.weight") for i in range(L)]),
            "bias": _stack([g(f"h.{i}.input_layernorm.bias") for i in range(L)]),
        },
        "ln2": {
            "scale": _stack([g(f"h.{i}.post_attention_layernorm.weight") for i in range(L)]),
            "bias": _stack([g(f"h.{i}.post_attention_layernorm.bias") for i in range(L)]),
        },
        "attn": {
            "wq": _stack(qw), "wk": _stack(kw), "wv": _stack(vw),
            "bq": _stack(qb), "bk": _stack(kb), "bv": _stack(vb),
            "wo": _stack([t(g(f"h.{i}.self_attention.dense.weight")) for i in range(L)]),
            "bo": _stack([g(f"h.{i}.self_attention.dense.bias") for i in range(L)]),
        },
        "mlp": {
            "w_up": _stack([t(g(f"h.{i}.mlp.dense_h_to_4h.weight")) for i in range(L)]),
            "b_up": _stack([g(f"h.{i}.mlp.dense_h_to_4h.bias") for i in range(L)]),
            "w_down": _stack([t(g(f"h.{i}.mlp.dense_4h_to_h.weight")) for i in range(L)]),
            "b_down": _stack([g(f"h.{i}.mlp.dense_4h_to_h.bias") for i in range(L)]),
        },
    }
    out = {
        "tok_embed": g("word_embeddings.weight"),
        "embed_norm": {
            "scale": g("word_embeddings_layernorm.weight"),
            "bias": g("word_embeddings_layernorm.bias"),
        },
        "layers": layers,
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    if not cfg.tie_embeddings:
        lm = state.get("lm_head.weight")
        out["lm_head"] = (
            t(lm) if lm is not None
            else np.ascontiguousarray(g("word_embeddings.weight").T)
        )
    return out


def _convert_falcon(state, cfg: ModelConfig) -> dict:
    """HF Falcon names → our layout. falcon-7b fuses q/k/v as
    [(H + 2)*hd, D] with ALL query heads first, then one k head, then one
    v head (multi_query — HF _split_heads' else branch); falcon-rw-style
    checkpoints (multi_query=False) use the per-head [H, 3, hd]
    interleave instead. Parallel attn+mlp share input_layernorm; no
    linear biases; layernorms keep theirs."""
    pre = "transformer." if any(k.startswith("transformer.") for k in state) else ""
    g = lambda k: state[pre + k]
    t = lambda a: np.ascontiguousarray(a.T)
    L, D = cfg.n_layers, cfg.d_model
    H, K, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    qw, kw, vw = [], [], []
    for i in range(L):
        w = g(f"h.{i}.self_attention.query_key_value.weight")
        if K == 1:  # multi_query: q block, then single k + v heads
            qw.append(t(w[: H * hd]))
            kw.append(t(w[H * hd: (H + 1) * hd]))
            vw.append(t(w[(H + 1) * hd:]))
        elif K == H:  # falcon-rw: [H, 3, hd] on the out dim
            wr = w.reshape(H, 3, hd, D)
            for dst, j in ((qw, 0), (kw, 1), (vw, 2)):
                dst.append(np.ascontiguousarray(wr[:, j].reshape(H * hd, D).T))
        else:
            raise ValueError(
                "falcon grouped-KV (new_decoder_architecture) checkpoints "
                "are not supported by the native loader"
            )
    layers = {
        "ln1": {
            "scale": _stack([g(f"h.{i}.input_layernorm.weight") for i in range(L)]),
            "bias": _stack([g(f"h.{i}.input_layernorm.bias") for i in range(L)]),
        },
        "attn": {
            "wq": _stack(qw), "wk": _stack(kw), "wv": _stack(vw),
            "wo": _stack([t(g(f"h.{i}.self_attention.dense.weight")) for i in range(L)]),
        },
        "mlp": {
            "w_up": _stack([t(g(f"h.{i}.mlp.dense_h_to_4h.weight")) for i in range(L)]),
            "w_down": _stack([t(g(f"h.{i}.mlp.dense_4h_to_h.weight")) for i in range(L)]),
        },
    }
    params = {
        "tok_embed": g("word_embeddings.weight"),
        "layers": layers,
        "final_norm": {"scale": g("ln_f.weight"), "bias": g("ln_f.bias")},
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = t(state["lm_head.weight"])
    return params


def _convert_neox(state, cfg: ModelConfig) -> dict:
    """HF GPT-NeoX/Pythia names → our layout. The fused query_key_value
    weight is [3*D, D] with rows ordered HEAD-MAJOR and q/k/v INTERLEAVED
    per head ([H, 3, hd] on the out dim — HF splits it after a
    view(B, T, H, 3*hd)); a naive thirds split would scramble heads."""
    pre = "gpt_neox." if any(k.startswith("gpt_neox.") for k in state) else ""
    g = lambda k: state[pre + k]
    t = lambda a: np.ascontiguousarray(a.T)
    L, D = cfg.n_layers, cfg.d_model
    H, hd = cfg.n_heads, cfg.head_dim

    def split_qkv(w, b):
        # w [3D, D] -> [H, 3, hd, D]; b [3D] -> [H, 3, hd]
        wr = w.reshape(H, 3, hd, D)
        br = b.reshape(H, 3, hd)
        ws = [np.ascontiguousarray(wr[:, i].reshape(H * hd, D).T) for i in range(3)]
        bs = [np.ascontiguousarray(br[:, i].reshape(H * hd)) for i in range(3)]
        return ws, bs

    qw, kw, vw, qb, kb, vb = [], [], [], [], [], []
    for i in range(L):
        ws, bs = split_qkv(
            g(f"layers.{i}.attention.query_key_value.weight"),
            g(f"layers.{i}.attention.query_key_value.bias"),
        )
        qw.append(ws[0]); kw.append(ws[1]); vw.append(ws[2])
        qb.append(bs[0]); kb.append(bs[1]); vb.append(bs[2])
    layers = {
        "ln1": {
            "scale": _stack([g(f"layers.{i}.input_layernorm.weight") for i in range(L)]),
            "bias": _stack([g(f"layers.{i}.input_layernorm.bias") for i in range(L)]),
        },
        "ln2": {
            "scale": _stack([g(f"layers.{i}.post_attention_layernorm.weight") for i in range(L)]),
            "bias": _stack([g(f"layers.{i}.post_attention_layernorm.bias") for i in range(L)]),
        },
        "attn": {
            "wq": _stack(qw), "wk": _stack(kw), "wv": _stack(vw),
            "bq": _stack(qb), "bk": _stack(kb), "bv": _stack(vb),
            "wo": _stack([t(g(f"layers.{i}.attention.dense.weight")) for i in range(L)]),
            "bo": _stack([g(f"layers.{i}.attention.dense.bias") for i in range(L)]),
        },
        "mlp": {
            "w_up": _stack([t(g(f"layers.{i}.mlp.dense_h_to_4h.weight")) for i in range(L)]),
            "b_up": _stack([g(f"layers.{i}.mlp.dense_h_to_4h.bias") for i in range(L)]),
            "w_down": _stack([t(g(f"layers.{i}.mlp.dense_4h_to_h.weight")) for i in range(L)]),
            "b_down": _stack([g(f"layers.{i}.mlp.dense_4h_to_h.bias") for i in range(L)]),
        },
    }
    return {
        "tok_embed": g("embed_in.weight"),
        "layers": layers,
        "final_norm": {
            "scale": g("final_layer_norm.weight"),
            "bias": g("final_layer_norm.bias"),
        },
        "lm_head": t(state["embed_out.weight"]),
    }


def _convert_phi3(state, cfg: ModelConfig) -> dict:
    """HF Phi-3 names → our layout. Architecturally phi-3 IS a llama-
    style model (rmsnorm, gated silu, GQA, rope) — only the tensor
    packing differs: qkv_proj fuses [q | k | v] on the out dim and
    gate_up_proj fuses [gate | up]. Un-fuse into llama key names and
    DELEGATE to _convert_llama, so every llama-branch behavior (norm
    folds, biases, future fixes) applies identically."""
    H, K, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    F = cfg.d_ff
    unfused: dict[str, np.ndarray] = {}
    for k, v in state.items():
        if k.endswith(".self_attn.qkv_proj.weight"):
            base = k.replace("qkv_proj", "{}")
            unfused[base.format("q_proj")] = v[: H * hd]
            unfused[base.format("k_proj")] = v[H * hd: (H + K) * hd]
            unfused[base.format("v_proj")] = v[(H + K) * hd:]
        elif k.endswith(".mlp.gate_up_proj.weight"):
            unfused[k.replace("gate_up_proj", "gate_proj")] = v[:F]
            unfused[k.replace("gate_up_proj", "up_proj")] = v[F:]
        else:
            unfused[k] = v
    return _convert_llama(unfused, cfg)


def _convert_llama(state, cfg: ModelConfig) -> dict:
    """HF Llama/Mistral names → our layout (weights transpose: HF linear is
    [out, in]; ours is [in, out])."""
    pre = "model." if any(k.startswith("model.") for k in state) else ""
    L = cfg.n_layers
    t = lambda a: np.ascontiguousarray(a.T)
    # gemma stores rmsnorm weights in the (1 + w) convention; our _norm
    # multiplies by scale directly, so fold the +1 in here
    norm_off = 1.0 if cfg.norm_plus_one else 0.0
    raw = lambda k: state[pre + k]
    g = lambda k: (raw(k) + norm_off) if "layernorm.weight" in k or k == "norm.weight" else raw(k)
    if cfg.post_norms and cfg.no_pre_norms:
        # olmo2: ONLY output norms — no input/pre_feedforward norms exist
        layers = {
            "ln1_post": {"scale": _stack([g(f"layers.{i}.post_attention_layernorm.weight") for i in range(L)])},
            "ln2_post": {"scale": _stack([g(f"layers.{i}.post_feedforward_layernorm.weight") for i in range(L)])},
        }
    elif cfg.post_norms:
        # gemma-2 names: post_attention_layernorm is the POST-attn output
        # norm (ours ln1_post); the pre-mlp norm is pre_feedforward_…
        layers = {
            "ln1": {"scale": _stack([g(f"layers.{i}.input_layernorm.weight") for i in range(L)])},
            "ln1_post": {"scale": _stack([g(f"layers.{i}.post_attention_layernorm.weight") for i in range(L)])},
            "ln2": {"scale": _stack([g(f"layers.{i}.pre_feedforward_layernorm.weight") for i in range(L)])},
            "ln2_post": {"scale": _stack([g(f"layers.{i}.post_feedforward_layernorm.weight") for i in range(L)])},
        }
    else:
        layers = {
            "ln1": {"scale": _stack([g(f"layers.{i}.input_layernorm.weight") for i in range(L)])},
            "ln2": {"scale": _stack([g(f"layers.{i}.post_attention_layernorm.weight") for i in range(L)])},
        }
        if cfg.norm == "layernorm" and cfg.norm_bias:  # stablelm: biased LNs
            layers["ln1"]["bias"] = _stack(
                [raw(f"layers.{i}.input_layernorm.bias") for i in range(L)])
            layers["ln2"]["bias"] = _stack(
                [raw(f"layers.{i}.post_attention_layernorm.bias") for i in range(L)])
    layers["attn"] = {
        "wq": _stack([t(g(f"layers.{i}.self_attn.q_proj.weight")) for i in range(L)]),
        "wk": _stack([t(g(f"layers.{i}.self_attn.k_proj.weight")) for i in range(L)]),
        "wv": _stack([t(g(f"layers.{i}.self_attn.v_proj.weight")) for i in range(L)]),
        "wo": _stack([t(g(f"layers.{i}.self_attn.o_proj.weight")) for i in range(L)]),
    }
    if pre + "layers.0.self_attn.q_proj.bias" in state:  # qwen2: q/k/v-only bias
        for ours, theirs in (("bq", "q_proj"), ("bk", "k_proj"), ("bv", "v_proj")):
            layers["attn"][ours] = _stack(
                [g(f"layers.{i}.self_attn.{theirs}.bias") for i in range(L)]
            )
    if pre + "layers.0.self_attn.q_norm.weight" in state:  # qwen3/gemma3
        # gemma-3's qk norms are zero-centered like its other norms —
        # fold the +1 here too (qwen3: norm_off is 0)
        for ours, theirs in (("q_norm", "q_norm"), ("k_norm", "k_norm")):
            layers["attn"][ours] = _stack(
                [raw(f"layers.{i}.self_attn.{theirs}.weight") + norm_off
                 for i in range(L)]
            )
    if cfg.is_moe:
        E = cfg.n_experts
        if pre + "layers.0.block_sparse_moe.gate.weight" in state:
            # mixtral names: block_sparse_moe.{gate, experts.N.w1/w2/w3}
            mb, gate_k, up_k, down_k = "block_sparse_moe", "w1", "w3", "w2"
            router_k = f"{mb}.gate"
            ek = lambda i, e, w: f"layers.{i}.{mb}.experts.{e}.{w}.weight"
        else:
            # qwen3_moe names: mlp.{gate, experts.N.gate/up/down_proj}
            gate_k, up_k, down_k = "gate_proj", "up_proj", "down_proj"
            router_k = "mlp.gate"
            ek = lambda i, e, w: f"layers.{i}.mlp.experts.{e}.{w}.weight"
        layers["moe"] = {
            "router": _stack([t(g(f"layers.{i}.{router_k}.weight")) for i in range(L)]),
            "w_gate": _stack([
                _stack([t(g(ek(i, e, gate_k))) for e in range(E)])
                for i in range(L)
            ]),
            "w_down": _stack([
                _stack([t(g(ek(i, e, down_k))) for e in range(E)])
                for i in range(L)
            ]),
            "w_up": _stack([
                _stack([t(g(ek(i, e, up_k))) for e in range(E)])
                for i in range(L)
            ]),
        }
    else:
        layers["mlp"] = {
            "w_gate": _stack([t(g(f"layers.{i}.mlp.gate_proj.weight")) for i in range(L)]),
            "w_up": _stack([t(g(f"layers.{i}.mlp.up_proj.weight")) for i in range(L)]),
            "w_down": _stack([t(g(f"layers.{i}.mlp.down_proj.weight")) for i in range(L)]),
        }
    params = {
        "tok_embed": g("embed_tokens.weight"),
        "layers": layers,
        "final_norm": {"scale": g("norm.weight")},
    }
    if cfg.norm == "layernorm" and cfg.norm_bias:
        params["final_norm"]["bias"] = raw("norm.bias")
    if not cfg.tie_embeddings:
        lm = state.get("lm_head.weight")
        params["lm_head"] = t(lm) if lm is not None else np.ascontiguousarray(g("embed_tokens.weight").T)
    return params


def _materialize(params, dtype, host: bool):
    """Cast the tree to `dtype` — on DEVICE normally, or as HOST numpy
    arrays (ml_dtypes handles bf16) when the caller wants to transform
    weights before the upload (e.g. int8 quantization: materializing the
    dense model in HBM first would double the load-time peak)."""
    if host:
        np_dtype = np.dtype(dtype)
        return jax.tree.map(lambda a: np.asarray(a).astype(np_dtype), params)
    return jax.tree.map(lambda a: jnp.asarray(a, dtype), params)


def load_checkpoint(
    path: str | Path, cfg: ModelConfig, dtype=jnp.bfloat16, host: bool = False
) -> dict:
    """Load a LOCAL checkpoint directory into our param pytree.

    Accepts: a dir with *.safetensors / pytorch_model*.bin (HF layout), or a
    dir produced by save_native(). host=True keeps the tree in host memory
    (see _materialize).
    """
    path = Path(path)
    if (path / "bee2bee_manifest.json").exists():
        return load_native(path, dtype=dtype, host=host)
    state = _load_hf_state(path)
    if any(".c_attn." in k for k in state):
        # gpt2 stores Conv1D [D, 3D]; gpt-bigcode stores Linear
        # [D + 2*kv_dim, D] — MQA configs and/or the transposed shape
        # identify the bigcode layout
        w0 = next(v for k, v in state.items() if k.endswith("attn.c_attn.weight"))
        if cfg.n_kv_heads != cfg.n_heads or w0.shape[0] != cfg.d_model:
            params = _convert_bigcode(state, cfg)
        else:
            params = _convert_gpt2(state, cfg)
    elif any(".mlp.fc1." in k for k in state):
        params = _convert_phi(state, cfg)
    elif any("word_embeddings_layernorm" in k for k in state):
        params = _convert_bloom(state, cfg)  # bloom's unique embed-LN key
    elif any(".attn.Wqkv." in k for k in state):  # mpt's unique fused name
        params = _convert_mpt(state, cfg)
    elif any(".self_attention.query_key_value." in k for k in state):
        # MUST precede the neox check: ".attention.query_key_value." is a
        # substring of falcon's ".self_attention.query_key_value."
        params = _convert_falcon(state, cfg)
    elif any(".attention.query_key_value." in k for k in state):
        params = _convert_neox(state, cfg)
    elif any(".self_attn.qkv_proj." in k for k in state):  # phi-3's fused
        params = _convert_phi3(state, cfg)
    elif any(".mlp.fc_in." in k for k in state):  # gpt-j's unique mlp names
        params = _convert_gptj(state, cfg)
    else:
        params = _convert_llama(state, cfg)
    return _materialize(params, dtype, host)


# ---- native format: content-addressed pieces + manifest ---------------------
# save_native/load_native double as the checkpoint/resume story AND the piece
# source for mesh weight distribution: the manifest is a pieces.ShardManifest.


def save_native(params, cfg: ModelConfig, path: str | Path, mesh_axes: dict[str, int] | None = None):
    from ..pieces import build_shard_manifest, save_pieces
    from .partition import flat_partition_specs

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    flat = _flatten(params)
    specs = (
        flat_partition_specs(params, mesh_axes, cfg=cfg)
        if mesh_axes
        else {k: () for k in flat}
    )
    manifest, blobs = build_shard_manifest(cfg.name, flat, specs, mesh_axes or {})
    save_pieces(list(blobs.values()), path / "pieces")
    (path / "bee2bee_manifest.json").write_text(manifest.to_json())
    (path / "model_config.json").write_text(json.dumps(cfg.__dict__, default=str))
    return manifest


def load_native(path: str | Path, dtype=jnp.bfloat16, host: bool = False) -> dict:
    from ..pieces import ShardManifest, load_piece

    path = Path(path)
    manifest = ShardManifest.from_json((path / "bee2bee_manifest.json").read_text())
    flat: dict[str, np.ndarray] = {}
    for piece in manifest.pieces:
        data = load_piece(path / "pieces", piece.sha256)
        arr = np.frombuffer(data, dtype=piece.dtype).reshape(piece.shape)
        if piece.shard_count > 1:
            flat.setdefault(piece.param, [None] * piece.shard_count)[piece.shard_index] = arr
        else:
            flat[piece.param] = arr
    for k, v in list(flat.items()):
        if isinstance(v, list):
            shard = next(p for p in manifest.pieces if p.param == k)
            flat[k] = np.concatenate(v, axis=shard.axis)
    params = _unflatten(flat)
    return _materialize(params, dtype, host)


def _flatten(params, prefix="") -> dict[str, np.ndarray]:
    out = {}
    for k, v in params.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: dict[str, np.ndarray]) -> dict:
    out: dict = {}
    for k, v in flat.items():
        parts = k.split("/")
        cur = out
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = v
    return out
