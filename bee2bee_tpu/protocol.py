"""Wire protocol: message constants + codec for the WebSocket mesh.

Wire-compatible with the reference message set (/root/reference/bee2bee/
protocol.py:17-53 and p2p_runtime.py:460-470) so the reference's JS bridge
(app/api/bridge.js:163-223) can talk to our nodes unmodified. Adds a binary
tensor frame codec the reference lacks — it ships tensors as JSON float lists
(node.py:96-98) which is ~5x the bytes; we send raw little-endian buffers
with a JSON header for the inter-peer pipeline/training paths.
"""

from __future__ import annotations

import json
import struct
from typing import Any

PROTOCOL_VERSION = 1
MAX_FRAME = 32 * 1024 * 1024  # reference cap (p2p_runtime.py:175,350)

# ---- mesh message types (reference protocol.py:17-34, p2p_runtime.py:460-470)
HELLO = "hello"
PEER_LIST = "peer_list"
PING = "ping"
PONG = "pong"
SERVICE_ANNOUNCE = "service_announce"
GEN_REQUEST = "gen_request"
GEN_CHUNK = "gen_chunk"
GEN_SUCCESS = "gen_success"
GEN_ERROR = "gen_error"
GEN_RESULT = "gen_result"
PIECE_REQUEST = "piece_request"
PIECE_DATA = "piece_data"
PIECE_HAVE = "piece_have"
GOODBYE = "goodbye"
# mesh health plane (health.py): a compact metrics digest gossiped on the
# ping cadence — NOT in the reference message set, but safe on the wire
# because the reference ignores unknown message types entirely
TELEMETRY = "telemetry"
# live generation migration (meshnet/migrate.py): a node exports an
# in-flight generation's KV blocks + decode state to a peer, which
# imports them into its own paged pool and resumes decoding token-for-
# token — drain/rebalance without re-prefill. KV_EXPORT carries the
# generation snapshot (JSON), KV_BLOCKS the hashed pool-block tensors
# (binary tensor frames, pieces.py-style sha256 per buffer), and
# KV_IMPORT_ACK the target's typed accept/reject. The resumed stream
# rides the existing GEN_CHUNK / GEN_SUCCESS / GEN_ERROR plumbing under
# the migration rid. Not in the reference message set (ignored by old
# peers — a migration to one simply times out and falls back).
KV_EXPORT = "kv_export"
KV_BLOCKS = "kv_blocks"
KV_IMPORT_ACK = "kv_import_ack"
# elastic fleet control loop (fleet/): a TTL'd controller lease gossiped
# mesh-wide (FLEET_LEASE — holder, monotonic epoch, ttl; receivers stamp
# ARRIVAL time, so no cross-node clock is compared), replica lifecycle
# commands from the lease holder (FLEET_ACTION — drain / undrain /
# activate / set_state / to_standby, epoch-gated so a split-brain loser
# or a stale controller cannot drain nodes), and the target's typed
# verdict (FLEET_ACK). Not in the reference message set — old peers
# ignore the frames, they just never participate in elasticity.
FLEET_LEASE = "fleet_lease"
FLEET_ACTION = "fleet_action"
FLEET_ACK = "fleet_ack"
# batched multi-LoRA serving (adapters/): a node whose adapter pool
# residency CHANGED (hot-swap fetch / eviction) broadcasts the new set so
# peers' provider tables track per-adapter model names ("<base>:<name>")
# without waiting for a re-hello — hello itself already carries the
# residency inside the service metadata. Not in the reference message
# set; old peers ignore the frame and simply route adapter traffic by
# the fuzzy model match alone.
ADAPTER_ANNOUNCE = "adapter_announce"
# mesh-tiered speculative decoding (meshnet/draft.py): a peer running the
# `draft` disagg role hosts ONLY a small drafter model; serving nodes
# stream per-row contexts to it and get K-token draft batches back.
# DRAFT_REQUEST carries {rid, base, tokens, k, model} — `base` is the
# context length the server already holds for rid, `tokens` the delta
# (base=0 resends from scratch; {rid, done:true} frees the row).
# DRAFT_RESULT answers {rid, pos, draft} where `pos` is the context
# length the draft continues from (the client drops stale results after
# a rejection re-sync), `reprime:true` asks the client for a full
# resend, and `error` is the server's typed failure. Pipelined one step
# ahead so the RTT hides under the target's own decode step; not in the
# reference message set (old peers ignore the frames — the client's
# timeout ladder degrades the row to the local drafter tier).
DRAFT_REQUEST = "draft_request"
DRAFT_RESULT = "draft_result"

# ---- coordinator/worker task protocol (reference protocol.py:25-53, node.py:89+)
REGISTER = "register"
INFO = "info"
TASK = "task"
RESULT = "result"
TASK_ERROR = "task_error"

TASK_LAYER_FORWARD = "layer_forward"
TASK_LAYER_FORWARD_TRAIN = "layer_forward_train"
TASK_LAYER_BACKWARD = "layer_backward"
TASK_MODEL_LOAD = "model_load"
TASK_MODEL_INFER = "model_infer"
TASK_MODEL_UNLOAD = "model_unload"
TASK_PART_LOAD = "part_load"
TASK_PART_FORWARD = "part_forward"
# relay chaining: hidden states hop stage→stage directly; only the last
# stage answers the coordinator (meshnet/pipeline.py)
TASK_PART_FORWARD_RELAY = "part_forward_relay"
# ring-burst decode: K greedy tokens circulate stage0→…→last→stage0
# with last-stage sampling; coordinator gets ONE result per burst
TASK_DECODE_RUN = "decode_run"
TASK_TRAIN_STEP = "train_step"

# task-failure classification, riding TASK_ERROR as an `error_kind` field:
# a coordinator must tell a DEAD stage (transport gone — replies can never
# arrive; failover re-places it) from a stage that is alive but FAILED the
# task (retry/fail, never re-place). Old peers omit the field, which
# classifies as ERR_KIND_ERROR — the conservative choice.
ERR_KIND_DEAD = "dead"
ERR_KIND_ERROR = "error"

MESSAGE_TYPES = frozenset(
    {
        HELLO,
        PEER_LIST,
        PING,
        PONG,
        SERVICE_ANNOUNCE,
        GEN_REQUEST,
        GEN_CHUNK,
        GEN_SUCCESS,
        GEN_ERROR,
        GEN_RESULT,
        PIECE_REQUEST,
        PIECE_DATA,
        PIECE_HAVE,
        GOODBYE,
        TELEMETRY,
        KV_EXPORT,
        KV_BLOCKS,
        KV_IMPORT_ACK,
        FLEET_LEASE,
        FLEET_ACTION,
        FLEET_ACK,
        ADAPTER_ANNOUNCE,
        DRAFT_REQUEST,
        DRAFT_RESULT,
        REGISTER,
        INFO,
        TASK,
        RESULT,
        TASK_ERROR,
    }
)


def msg(type_: str, **fields: Any) -> dict:
    """Build a message dict (reference protocol.py:9-12)."""
    out = {"type": type_}
    out.update(fields)
    return out


def encode(message: dict) -> str:
    return json.dumps(message, separators=(",", ":"))


def decode(raw: str | bytes) -> dict:
    if isinstance(raw, bytes):
        return decode_binary(raw)[0]
    obj = json.loads(raw)
    if not is_message(obj):
        raise ValueError("not a protocol message")
    return obj


def is_message(obj: Any) -> bool:
    return isinstance(obj, dict) and isinstance(obj.get("type"), str)


# ---- binary tensor frames ----------------------------------------------------
# Layout: magic b"B2T1" | u32 header_len | header JSON (utf-8) | payload bytes.
# Header carries {"type":..., any fields..., "tensors": [{"name","dtype","shape",
# "nbytes"}...]}; tensor buffers are concatenated in order after the header.

_MAGIC = b"B2T1"


def encode_binary(message: dict, tensors: dict[str, "Any"] | None = None) -> bytes:
    import numpy as np

    import ml_dtypes  # noqa: F401 — registers bfloat16 with numpy: pipeline
    # hidden states ship as bf16 (half the bytes of f32 at full exponent range)

    tensors = tensors or {}
    specs = []
    buffers = []
    for name, arr in tensors.items():
        a = np.asarray(arr)
        # record the shape BEFORE ascontiguousarray: numpy promotes 0-d
        # inputs to 1-d there, which silently mangled scalar tensors
        shape = list(a.shape)
        a = np.ascontiguousarray(a)
        specs.append(
            {"name": name, "dtype": str(a.dtype), "shape": shape, "nbytes": a.nbytes}
        )
        buffers.append(a.tobytes())
    if "tensors" in message:
        # reserved: the header slot the specs ride in — a message field of
        # that name would be silently clobbered here and popped on decode
        raise ValueError("'tensors' is a reserved message field")
    header = dict(message)
    header["tensors"] = specs
    hb = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return _MAGIC + struct.pack("<I", len(hb)) + hb + b"".join(buffers)


def decode_binary(raw: bytes) -> tuple[dict, dict]:
    """Returns (message, tensors). `message` keeps non-tensor fields."""
    import numpy as np

    import ml_dtypes  # noqa: F401 — bfloat16 dtype strings must resolve

    if raw[:4] != _MAGIC:
        raise ValueError("bad tensor-frame magic")
    if len(raw) < 8:
        raise ValueError("truncated tensor-frame header")
    (hlen,) = struct.unpack("<I", raw[4:8])
    if len(raw) < 8 + hlen:
        raise ValueError("truncated tensor-frame header")
    header = json.loads(raw[8 : 8 + hlen].decode("utf-8"))
    specs = header.pop("tensors", [])
    tensors = {}
    off = 8 + hlen
    for spec in specs:
        n = spec["nbytes"]
        buf = raw[off : off + n]
        if len(buf) != n:
            raise ValueError("truncated tensor frame")
        tensors[spec["name"]] = np.frombuffer(buf, dtype=spec["dtype"]).reshape(spec["shape"])
        off += n
    if not is_message(header):
        raise ValueError("not a protocol message")
    return header, tensors


# multi-adapter serving (adapters/): which LoRA adapter a generation runs
# under, riding GEN_REQUEST as an optional key (the "<base>:<adapter>"
# model form parses to the same thing — adapters.split_model_adapter is
# the one rule). Receivers CLAMP the claim (adapters.clamp_adapter_name)
# and answer a typed unknown_adapter GEN_ERROR when nothing resolves —
# a wire string must never mint metric series or DHT keys.
ADAPTER = "adapter"

# per-tenant serving identity (router/): resolved from the API key at the
# gateway, riding GEN_REQUEST (and relay hops) as an optional key so the
# serving node's admission controller and scheduler fairness see the SAME
# tenant the front door billed. Old peers ignore it; receivers clamp
# unconfigured claims to the default tenant (TenantRegistry.clamp) so a
# hostile frame can't mint metric series. Declared in analysis/schema.py.
TENANT = "tenant"

# cross-node trace propagation (tracing.py): the originating request's
# (trace_id, span_id) rides gen_request / task / result frames under this
# optional key so worker-side spans parent under the request that caused
# them. The reference mesh ignores unknown keys, so old peers are
# unaffected; receivers treat a missing/malformed value as "no context".
TRACE_CTX = "trace_ctx"

# sampling knobs that ride GEN_REQUEST as plain message keys (the
# reference ignores unknown keys, so frames stay wire-compatible). ONE
# list: the gateway, the node handler, and the relay all copy from it —
# a key present here but missing at any hop is a silently-wrong output.
SAMPLING_KEYS = (
    "top_k",
    "top_p",
    "min_p",
    "repetition_penalty",
    "presence_penalty",
    "frequency_penalty",
    # not a sampler knob, but a generation param with the same contract:
    # OpenAI `stop` strings (str or list), consumed at the service layer
    "stop",
)


def copy_sampling(src: dict, dst: dict) -> dict:
    """Copy present-and-not-None sampling knobs from src into dst."""
    for k in SAMPLING_KEYS:
        if src.get(k) is not None:
            dst[k] = src[k]
    return dst
