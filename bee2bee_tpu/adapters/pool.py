"""AdapterPool: N hot-swappable LoRA adapters resident over ONE base model.

The multi-tenant serving core (ROADMAP item 1): instead of merging one
adapter into the base weights at load (`serve-tpu --lora`, which bakes a
single tenant into the engine), the pool keeps every adapter's low-rank
A/B factors stacked on device —

    {target: {"a": [L, N+1, din, r], "b": [L, N+1, r, dout]}}  (f32)

— and the jitted step gathers each batch ROW's slot (models/core.
lora_matmul), so a mixed batch serves N tenants in one forward. Slot 0 is
the reserved NULL adapter (all-zero factors, scaling 0): adapter-less
rows in a mixed batch gather zeros and stay bit-exact, and a batch with
no adapter rows skips the lora arguments entirely (the scheduler's
batch-level gate — same per-row discipline spec decode established).

Geometry is fixed by the FIRST adapter loaded (or pinned explicitly):
layer layout from the model config, rank = that adapter's rank, targets =
its target set. Later adapters may use a smaller rank (factors zero-pad
to the pool rank — the delta is unchanged) and any subset of the pool's
targets (missing targets stay zero); a larger rank or a new target is a
typed AdapterLoadError, never a shape crash inside jit.

Slots recycle LRU among adapters with no in-flight rows: the scheduler
acquire()s a slot at admission and release()s it at retirement, so a
hot-swap (fetch over the DHT, evict a cold adapter) can never yank the
factors out from under a live generation. Pool arrays are never donated —
an in-flight decode keeps reading the buffers it was dispatched with,
and a load() swaps in fresh arrays for the NEXT step.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics import get_registry
from ..train.lora import (
    ATTN_TARGETS,
    MLP_TARGETS,
    AdapterLoadError,
    LoraConfig,
    adapter_target_io,
    validate_adapter_shapes,
)

# pool observability (the satellite's /metrics surface): residency gauge,
# load/evict counters, and per-adapter request counts. The `adapter`
# label is bounded by what the pool ever admitted — the scheduler only
# counts RESOLVED slots, so a hostile wire string can't mint series.
_G_RESIDENT = get_registry().gauge(
    "adapter.pool_resident", "LoRA adapters resident in the pool"
)
_C_LOADS = get_registry().counter(
    "adapter.pool_loads", "adapters loaded (fresh or refreshed) into the pool"
)
_C_EVICTED = get_registry().counter(
    "adapter.pool_evicted", "adapters evicted from the pool"
)
_C_REQUESTS = get_registry().counter(
    "adapter.requests", "generations admitted per adapter"
)


from . import AdapterPoolBusy, UnknownAdapter  # noqa: F401 — canonical
# definitions live in the import-light package root so api/meshnet can
# catch them without pulling jax; re-exported here for pool-side callers


class AdapterPool:
    """See module docstring. Thread-safety: the host maps and the device
    array references swap under one lock; device arrays themselves are
    immutable, so a scheduler thread that snapshotted ``device_args()``
    keeps a consistent (factors, scales) pair for its whole step."""

    def __init__(self, model_cfg, slots: int):
        if slots < 1:
            raise ValueError(f"adapter pool needs >= 1 slot, got {slots}")
        self.model_cfg = model_cfg
        self.slots = int(slots)
        self._lock = threading.Lock()
        # serializes WRITERS (load) across their whole host-prep +
        # device-build; the fast _lock above is what the scheduler's
        # device_args()/acquire() take and is only ever held for
        # bookkeeping and reference swaps. Order: _io_lock → _lock.
        self._io_lock = threading.Lock()
        # geometry (rank/targets) binds on the first load
        self.rank: int | None = None
        self.targets: tuple | None = None
        self._device: dict | None = None  # {t: {"a","b"}} stacked on device
        self._scales = None  # [slots+1] f32 device array (slot 0 -> 0.0)
        self._by_name: dict[str, int] = {}  # name -> slot (1-based)
        self._by_slot: dict[int, str] = {}
        self._refs: dict[int, int] = {}  # slot -> in-flight rows
        self._tick = 0  # LRU clock
        self._last_used: dict[int, int] = {}
        self.loads = 0
        self.evictions = 0
        # one jitted slot write per (a|b, target shape): slot rides as a
        # traced scalar so swapping different slots never recompiles
        self._set_slot = jax.jit(
            lambda arr, new, slot: jax.lax.dynamic_update_slice(
                arr, new[:, None], (0, slot, 0, 0)
            )
        )

    # ------------------------------------------------------------ geometry

    def _ensure_geometry(self, lcfg: LoraConfig):
        if self.rank is not None:
            return
        for t in lcfg.targets:
            if t not in ATTN_TARGETS + MLP_TARGETS:
                raise AdapterLoadError(f"unknown adapter target {t!r}")
        io = adapter_target_io(self.model_cfg)
        L = self.model_cfg.n_layers
        self.rank = int(lcfg.rank)
        self.targets = tuple(lcfg.targets)
        N = self.slots + 1  # + the null slot 0
        self._device = {
            t: {
                "a": jnp.zeros((L, N, io[t][0], self.rank), jnp.float32),
                "b": jnp.zeros((L, N, self.rank, io[t][1]), jnp.float32),
            }
            for t in self.targets
        }
        self._scales = jnp.zeros((N,), jnp.float32)

    # ------------------------------------------------------------ load/evict

    def _pick_slot(self) -> int:
        free = [
            s for s in range(1, self.slots + 1) if s not in self._by_slot
        ]
        if free:
            return free[0]
        idle = [
            s for s in range(1, self.slots + 1) if self._refs.get(s, 0) == 0
        ]
        if not idle:
            raise AdapterPoolBusy(
                f"all {self.slots} adapter slots have in-flight rows"
            )
        victim = min(idle, key=lambda s: self._last_used.get(s, 0))
        name = self._by_slot.pop(victim)
        self._by_name.pop(name, None)
        self.evictions += 1
        _C_EVICTED.inc()
        return victim

    def _write_slot(self, snapshot: dict, host: dict, slot: int,
                    targets: tuple) -> dict:
        """New device dict with `slot`'s factors replaced from the host-
        prepped `host` map (None entry = zero the target). Reads only the
        passed snapshot — callers guarantee no concurrent writer via
        _io_lock."""
        device = dict(snapshot)
        for t in targets:
            pair = host.get(t)
            sa = snapshot[t]["a"].shape  # [L, N, din, R]
            sb = snapshot[t]["b"].shape
            if pair is None:  # target absent from this adapter: zeros
                a = np.zeros((sa[0], sa[2], sa[3]), np.float32)
                b = np.zeros((sb[0], sb[2], sb[3]), np.float32)
            else:
                a, b = pair
            device[t] = {
                "a": self._set_slot(snapshot[t]["a"], a, slot),
                "b": self._set_slot(snapshot[t]["b"], b, slot),
            }
        return device

    def _publish_locked(self, name: str, slot: int, device: dict,
                        lcfg: LoraConfig) -> int:
        """Swap the built device arrays + bookkeeping in. Caller holds
        _lock — this is the ONLY part of a load the scheduler can ever
        wait on."""
        self._device = device
        self._scales = self._scales.at[slot].set(float(lcfg.scaling))
        self._by_name[name] = slot
        self._by_slot[slot] = name
        self._tick += 1
        self._last_used[slot] = self._tick
        self.loads += 1
        _C_LOADS.inc()
        _G_RESIDENT.set(len(self._by_name))
        return slot

    def load(self, name: str, adapters: dict, lcfg: LoraConfig) -> int:
        """Pin `name`'s factors into a slot (fresh, refreshed in place, or
        LRU-evicting a cold adapter). Validates shapes against the pool
        geometry FIRST — a rank/target mismatch is a typed
        AdapterLoadError with the pool untouched. Returns the slot.

        Locking: _io_lock serializes writers over the whole build; the
        scheduler-facing _lock is held only for bookkeeping and the
        final reference swap, so device_args()/acquire() never stall
        behind the MB-scale host copies, H2D transfers, or a first-use
        jit compile — live decode continues through a hot-swap."""
        if not name or not isinstance(name, str):
            raise AdapterLoadError(f"adapter name must be a string, got {name!r}")
        with self._io_lock:
            with self._lock:
                rank, targets = self.rank, self.targets
            # validate BEFORE the geometry binds: a corrupt first adapter
            # must leave the pool untouched, not fix rank/targets to its
            # bad declaration until restart
            validate_adapter_shapes(
                self.model_cfg, adapters, lcfg, max_rank=rank
            )
            if targets is not None:
                extra = set(lcfg.targets) - set(targets)
                if extra:
                    raise AdapterLoadError(
                        f"adapter {name!r} targets {sorted(extra)} not in pool "
                        f"targets {sorted(targets)} (fixed by the first "
                        "adapter loaded)"
                    )
            # host-side prep (device_get + rank padding) with no lock a
            # reader ever takes
            pool_rank = rank if rank is not None else int(lcfg.rank)
            pool_targets = (
                targets if targets is not None else tuple(lcfg.targets)
            )
            host: dict = {}
            for t in pool_targets:
                ab = adapters.get(t)
                if ab is None:
                    host[t] = None
                    continue
                a = np.asarray(jax.device_get(ab["a"]), np.float32)
                b = np.asarray(jax.device_get(ab["b"]), np.float32)
                if lcfg.rank < pool_rank:
                    # zero-pad the rank dim: delta unchanged, one
                    # stacked shape for the whole pool
                    a = np.pad(a, ((0, 0), (0, 0), (0, pool_rank - lcfg.rank)))
                    b = np.pad(b, ((0, 0), (0, pool_rank - lcfg.rank), (0, 0)))
                host[t] = (a, b)
            with self._lock:
                self._ensure_geometry(lcfg)
                slot = self._by_name.get(name)
                if slot is not None:
                    if self._refs.get(slot, 0) > 0:
                        # an in-place refresh would hand a LIVE generation
                        # new factors at its next decode window — mixed-
                        # weights output. Same typed backpressure as
                        # eviction.
                        raise AdapterPoolBusy(
                            f"adapter {name!r} has in-flight rows; "
                            "cannot refresh"
                        )
                    # refresh stays atomic under _lock: an unlocked build
                    # window would let acquire() admit a row against the
                    # OLD factors that then decodes on the NEW ones
                    device = self._write_slot(
                        self._device, host, slot, pool_targets
                    )
                    return self._publish_locked(name, slot, device, lcfg)
                slot = self._pick_slot()
                snapshot = self._device
            # FRESH slot: no name maps to it until _publish_locked below,
            # so no acquire() can race this build — the H2D dispatches
            # run without stalling the decode loop
            device = self._write_slot(snapshot, host, slot, pool_targets)
            with self._lock:
                return self._publish_locked(name, slot, device, lcfg)

    def evict(self, name: str) -> bool:
        """Explicitly drop a resident adapter (refetch tests, operator
        surface). Refuses — AdapterPoolBusy — while rows are in flight."""
        with self._lock:
            slot = self._by_name.get(name)
            if slot is None:
                return False
            if self._refs.get(slot, 0) > 0:
                raise AdapterPoolBusy(
                    f"adapter {name!r} has in-flight rows; cannot evict"
                )
            self._by_name.pop(name)
            self._by_slot.pop(slot, None)
            # zero the scaling so a stale id (never handed out past this
            # point, but defense in depth) gathers a zero delta
            self._scales = self._scales.at[slot].set(0.0)
            self.evictions += 1
            _C_EVICTED.inc()
            _G_RESIDENT.set(len(self._by_name))
            return True

    # ------------------------------------------------------------ row leases

    def acquire(self, name: str) -> int:
        """Slot for `name`, with its in-flight refcount bumped (the
        scheduler calls this at admission; release() at retirement). The
        refcount is what makes hot-swap safe mid-traffic: a referenced
        slot is never an eviction victim."""
        with self._lock:
            slot = self._by_name.get(name)
            if slot is None:
                raise UnknownAdapter(f"adapter {name!r} is not resident")
            self._refs[slot] = self._refs.get(slot, 0) + 1
            self._tick += 1
            self._last_used[slot] = self._tick
            _C_REQUESTS.inc(adapter=name)
            return slot

    def release(self, slot: int) -> None:
        with self._lock:
            left = self._refs.get(slot, 0) - 1
            if left <= 0:
                self._refs.pop(slot, None)
            else:
                self._refs[slot] = left

    # ------------------------------------------------------------ queries

    def has(self, name: str) -> bool:
        with self._lock:
            return name in self._by_name

    def resident(self) -> list[str]:
        with self._lock:
            return sorted(self._by_name)

    def slot_of(self, name: str) -> int | None:
        with self._lock:
            return self._by_name.get(name)

    def device_args(self):
        """(stacked factors pytree, [N+1] scales) for the jitted step, or
        (None, None) before the first load. One lock-held read gives the
        scheduler a consistent snapshot for a whole decode window."""
        with self._lock:
            return self._device, self._scales

    @property
    def info(self) -> dict:
        with self._lock:
            return {
                "slots": self.slots,
                "rank": self.rank,
                "targets": list(self.targets or ()),
                "resident": sorted(self._by_name),
                "loads": self.loads,
                "evictions": self.evictions,
            }
