"""Adapter paging over the mesh: publish/fetch LoRA factors as sha256-
verified pieces manifests on the DHT.

The weights publish→DHT→fetch leg (meshnet/weights.py) moves multi-GB
base checkpoints; adapters reuse the exact same discipline at MB scale —
one ShardManifest per adapter (every tensor a replicated, content-
addressed piece), announced under the namespaced manifest key
``adapter/<base>/<name>``, pieces served over the mesh's binary piece
frames with per-piece sha256 verified before anything reaches a pool.
The LoraConfig rides as one extra JSON piece (``__lora_cfg__``), so a
fetching node can validate rank/targets (train/lora.
validate_adapter_shapes) BEFORE factors go near its AdapterPool.
"""

from __future__ import annotations

import asyncio
import json
import logging

import numpy as np

from ..train.lora import AdapterLoadError, LoraConfig, validate_adapter_shapes
from ..utils import sha256_hex

logger = logging.getLogger("bee2bee_tpu.adapters")

_CFG_PIECE = "__lora_cfg__"
FETCH_CONCURRENCY = 8


def adapter_key(base_model: str, name: str) -> str:
    """The DHT manifest key for one adapter. '/' never appears in model
    or adapter names (clamp_adapter_name), so keys cannot alias."""
    return f"adapter/{base_model}/{name}"


def _cfg_blob(lcfg: LoraConfig) -> bytes:
    return json.dumps(
        {"rank": lcfg.rank, "alpha": lcfg.alpha, "targets": list(lcfg.targets)},
        separators=(",", ":"),
    ).encode("utf-8")


def _cfg_from_blob(blob: bytes) -> LoraConfig:
    try:
        obj = json.loads(blob.decode("utf-8"))
        return LoraConfig(
            rank=int(obj["rank"]), alpha=float(obj["alpha"]),
            targets=tuple(obj["targets"]),
        )
    except AdapterLoadError:
        raise
    except Exception as e:  # noqa: BLE001 — malformed publisher blob
        raise AdapterLoadError(f"malformed adapter config piece: {e}") from e


async def publish_adapter(node, dht, base_model: str, name: str,
                          adapters: dict, lcfg: LoraConfig):
    """Shard one adapter into content-addressed pieces, seed the node's
    piece store, and announce manifest + providers on the DHT. Returns
    the ShardManifest. Factors are validated against nothing here — the
    publisher may not even hold the base model config; every FETCHING
    node validates before its pool (fetch_adapter)."""
    import jax

    from ..models.loader import _flatten
    from ..pieces import build_shard_manifest

    key = adapter_key(base_model, name)
    flat = {
        k: np.asarray(jax.device_get(v), np.float32)
        for k, v in _flatten(adapters).items()
    }
    flat[_CFG_PIECE] = np.frombuffer(_cfg_blob(lcfg), dtype=np.uint8)
    # every piece replicated (mesh_axes={}): rank-r factors never shard
    manifest, blobs = build_shard_manifest(
        key, flat, {k: () for k in flat}, {}
    )
    for digest, blob in blobs.items():
        node.piece_store[digest] = blob
    node.manifests[key] = manifest
    await dht.announce_manifest(key, manifest.to_json(), node.addr)
    sem = asyncio.Semaphore(FETCH_CONCURRENCY)

    async def announce(piece):
        async with sem:
            await dht.announce_piece(piece.sha256, node.addr)

    await asyncio.gather(*(announce(p) for p in manifest.pieces))
    logger.info(
        "published adapter %s: %d pieces, %.2f MiB",
        key, len(manifest.pieces), manifest.total_bytes / 2**20,
    )
    return manifest


async def fetch_adapter(node, dht, base_model: str, name: str,
                        model_cfg=None) -> tuple[dict, LoraConfig]:
    """Fetch one adapter's manifest + pieces from mesh providers; returns
    (adapters pytree, LoraConfig), hash-verified and — when ``model_cfg``
    is given — shape-validated (typed AdapterLoadError otherwise)."""
    from ..meshnet.weights import _peer_for_addr
    from ..models.loader import _unflatten
    from ..pieces import ShardManifest

    key = adapter_key(base_model, name)
    rec = await dht.get_manifest(key)
    if rec is None:
        raise UnknownAdapterManifest(
            f"no adapter manifest on the DHT for {key!r}"
        )
    manifest = ShardManifest.from_json(rec["manifest"])

    sem = asyncio.Semaphore(FETCH_CONCURRENCY)
    blobs: dict[str, bytes] = {}

    async def fetch(piece):
        local = node.get_piece(piece.sha256)
        if local is not None:
            blobs[piece.sha256] = local
            return
        providers = await dht.find_providers(piece.sha256)
        addrs = [p["addr"] for p in providers] or [rec.get("addr")]
        last_err: Exception | None = None
        async with sem:
            for addr in addrs:
                if not addr:
                    continue
                try:
                    pid = await _peer_for_addr(node, addr)
                    if pid is None:
                        continue
                    blobs[piece.sha256] = await node.request_piece(
                        pid, piece.sha256
                    )
                    return
                except Exception as e:  # noqa: BLE001 — next provider
                    last_err = e
        raise RuntimeError(
            f"no provider served adapter piece {piece.sha256[:12]} "
            f"for {piece.param}"
        ) from last_err

    results = await asyncio.gather(
        *(fetch(p) for p in manifest.pieces), return_exceptions=True
    )
    errors = [r for r in results if isinstance(r, BaseException)]
    if errors:
        raise errors[0]

    flat: dict[str, np.ndarray] = {}
    cfg_blob: bytes | None = None
    for p in manifest.pieces:
        data = blobs[p.sha256]
        if sha256_hex(data) != p.sha256:
            raise AdapterLoadError(
                f"adapter piece corrupt for {p.param} ({p.sha256[:12]})"
            )
        if p.param == _CFG_PIECE:
            cfg_blob = data
            continue
        flat[p.param] = np.frombuffer(data, dtype=p.dtype).reshape(p.shape)
    if cfg_blob is None:
        raise AdapterLoadError(f"adapter manifest {key!r} has no config piece")
    lcfg = _cfg_from_blob(cfg_blob)
    adapters = _unflatten(flat)
    if model_cfg is not None:
        validate_adapter_shapes(model_cfg, adapters, lcfg)
    return adapters, lcfg


class UnknownAdapterManifest(KeyError):
    """No manifest for the requested adapter anywhere on the DHT — the
    typed 'this adapter does not exist in the mesh' verdict (the serving
    path maps it to unknown_adapter / 404)."""

    def __str__(self):
        return self.args[0] if self.args else "unknown adapter manifest"
