"""Batched multi-LoRA serving: one resident base model, N hot-swappable
adapters (ROADMAP item 1).

- ``pool`` — AdapterPool: stacked per-target A/B factors on device, per-
  row slot gather inside the jitted step (models/core.lora_matmul),
  LRU slot recycling guarded by in-flight refcounts.
- ``distrib`` — adapters as sha256-verified pieces manifests on the DHT
  (the weights publish→DHT→fetch leg, at adapter scale): publish once,
  any node pages the factors in without restarting its engine.

Naming: a served adapter model is ``<base>:<adapter>`` (``/v1`` model
ids, mesh hello/announce, router placement) — ``split_model_adapter``
is the ONE parser every surface shares.
"""

from __future__ import annotations

class UnknownAdapter(KeyError):
    """The requested adapter is not resident (and could not be resolved).
    Typed so the serving surfaces answer a clean 404 / unknown_adapter
    instead of a generic failure. Lives HERE (not pool.py) so api.py and
    meshnet can catch it without importing the jax-heavy pool."""

    def __str__(self):  # KeyError quotes its arg; keep the message usable
        return self.args[0] if self.args else "unknown adapter"


class AdapterPoolBusy(RuntimeError):
    """Every slot's adapter has in-flight rows — nothing can be evicted.
    Backpressure, not corruption: the caller retries or routes elsewhere."""


# the pool (and the train.lora machinery behind it) imports jax/optax;
# this package root stays import-light because meshnet/node.py and
# api.py pull the naming helpers on every boot — the heavy classes
# resolve lazily via __getattr__
_LAZY = {
    "AdapterPool": (".pool", "AdapterPool"),
    "AdapterLoadError": ("bee2bee_tpu.train.lora", "AdapterLoadError"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod, attr = _LAZY[name]
        module = (
            importlib.import_module(mod, package=__name__)
            if mod.startswith(".") else importlib.import_module(mod)
        )
        return getattr(module, attr)
    raise AttributeError(name)

# wire-safety clamp for the gen_request `adapter` key: names key metric
# labels and DHT keys, so an unbounded or exotic wire string must reduce
# to None (→ typed unknown_adapter) rather than flow onward
MAX_ADAPTER_NAME = 64


def clamp_adapter_name(name) -> str | None:
    """A wire-supplied adapter claim → a sane name or None. ':' is the
    model separator and '/' the DHT key separator — a name containing
    either could alias another adapter's key."""
    if not isinstance(name, str) or not name:
        return None
    if len(name) > MAX_ADAPTER_NAME or ":" in name or "/" in name:
        return None
    return name


def split_model_adapter(model) -> tuple[str | None, str | None]:
    """``"<base>:<adapter>"`` → (base, adapter); a plain model name (or
    None) passes through with adapter None. Only the FIRST colon splits.
    The adapter half is returned RAW — callers clamp it and must treat a
    clamp failure as a typed unknown_adapter, never as "no adapter":
    collapsing a malformed name to None here would silently serve the
    plain base model to a tenant that asked for an adapter."""
    if not isinstance(model, str) or ":" not in model:
        return model, None
    base, _, name = model.partition(":")
    return base or None, name


def adapter_model_name(base: str, adapter: str) -> str:
    return f"{base}:{adapter}"
