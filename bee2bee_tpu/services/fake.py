"""FakeService: deterministic in-memory backend for tests.

SURVEY §4: "no fake model backend (tests simply skip model paths)" is a
reference gap we close — the whole mesh/gateway stack is testable without
loading a model.
"""

from __future__ import annotations

import time
from typing import Any, Iterator

from .base import BaseService


class FakeService(BaseService):
    def __init__(
        self,
        model_name: str = "fake-model",
        price_per_token: float = 0.0,
        reply: str | None = None,
        chunk_size: int = 4,
        fail_with: str | None = None,
        delay_s: float = 0.0,  # per-chunk stream delay (chaos/latency tests)
        exec_delay_s: float = 0.0,  # whole-execute() delay: makes a node
        # saturable for admission/fairness tests and the bench rung
    ):
        super().__init__("fake")
        self.model_name = model_name
        self.price_per_token = price_per_token
        self.reply = reply
        self.chunk_size = chunk_size
        self.fail_with = fail_with
        self.delay_s = delay_s
        self.exec_delay_s = exec_delay_s
        self.calls: list[dict] = []

    def get_metadata(self) -> dict[str, Any]:
        return {
            "models": [self.model_name],
            "price_per_token": self.price_per_token,
            "max_new_tokens": 2048,
        }

    def _reply_for(self, params: dict) -> str:
        if self.reply is not None:
            return self.reply
        return f"echo({self._require_prompt(params)})"

    def _timing(self, t0: float, n_tokens: int) -> dict:
        """Engine-shaped per-request breakdown (engine.py _build_result):
        the mesh/gateway timing plumbing is testable without a model."""
        e2e_ms = (time.time() - t0) * 1000.0
        return {
            "queue_wait_ms": 0.0,
            "prefill_ms": round(e2e_ms, 3),
            "ttft_ms": round(e2e_ms, 3),
            "decode_tokens": n_tokens,
            "tokens_per_s": (
                round(n_tokens / (e2e_ms / 1000.0), 2) if e2e_ms > 0 else 0.0
            ),
            "spec_acceptance": None,
        }

    def execute(self, params: dict[str, Any]) -> dict[str, Any]:
        self.calls.append(dict(params))
        if self.fail_with:
            from .base import ServiceError

            raise ServiceError(self.fail_with)
        t0 = time.time()
        if self.exec_delay_s:
            time.sleep(self.exec_delay_s)  # runs in the node's executor
        text = self._reply_for(params)
        n = len(text.split())
        out = self.result_dict(text, n, t0, self.price_per_token)
        out["timing"] = self._timing(t0, n)
        return out

    def execute_stream(self, params: dict[str, Any]) -> Iterator[str]:
        self.calls.append(dict(params))
        if self.fail_with:
            yield self.stream_line({"status": "error", "message": self.fail_with})
            return
        t0 = time.time()
        text = self._reply_for(params)
        for i in range(0, len(text), self.chunk_size):
            if self.delay_s:
                time.sleep(self.delay_s)
            yield self.stream_line({"text": text[i : i + self.chunk_size]})
        n = len(text.split())  # same accounting as execute()
        yield self.stream_line(
            {"done": True, "tokens": n, "cost": self.price_per_token * n,
             "timing": self._timing(t0, n)}
        )
