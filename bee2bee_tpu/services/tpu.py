"""TPUService: the serving backend — wraps InferenceEngine behind the
BaseService contract (the role HFService plays in the reference,
services.py:27-116, with torch generate swapped for the jit engine).
"""

from __future__ import annotations

import time
from typing import Any, Iterator

from .base import (
    BaseService,
    ServiceError,
    normalize_stops,
    parse_transcript,
    role_cut,
    scrub_stop_words,
    scrub_stream_delta,
    stop_cut,
)


class TPUService(BaseService):
    def __init__(
        self,
        model_name: str,
        price_per_token: float = 0.0,
        max_new_tokens: int = 2048,
        engine=None,
        mesh=None,
        checkpoint_path: str | None = None,
        engine_config=None,
        lora_path: str | None = None,
    ):
        super().__init__("tpu")
        self.model_name = model_name
        self.price_per_token = price_per_token
        self.max_new_tokens = max_new_tokens
        self.engine = engine
        self._mesh = mesh
        self._checkpoint_path = checkpoint_path
        self._engine_config = engine_config
        self._lora_path = lora_path

    # loading is split from construction so nodes can announce before the
    # (slow) compile finishes — same shape as the reference's load_sync/
    # load_async split (services.py:36-41)
    def load_sync(self):
        if self.engine is None:
            from ..engine.engine import InferenceEngine

            self.engine = InferenceEngine(
                self.model_name,
                mesh=self._mesh,
                checkpoint_path=self._checkpoint_path,
                engine_config=self._engine_config,
                lora_path=self._lora_path,
            )
        if self.model_name in (None, "", "auto"):
            # `--model auto`: advertise the name the checkpoint's config
            # resolved to, not the sentinel
            self.model_name = self.engine.model_cfg.name
        return self

    def get_metadata(self) -> dict[str, Any]:
        meta = {
            "models": [self.model_name],
            "price_per_token": self.price_per_token,
            "max_new_tokens": self.max_new_tokens,
            "backend": "tpu",
        }
        if self.engine is not None:
            meta["engine"] = self.engine.info
            meta["measured"] = self.engine.metrics.snapshot()
            resident = self.engine.resident_adapters()
            if resident:
                # per-adapter model names (adapters/): "<base>:<name>"
                # rides hello/announce metadata so the mesh can route an
                # adapter request straight to a node already holding it
                from ..adapters import adapter_model_name

                meta["adapters"] = resident
                meta["models"] = [self.model_name] + [
                    adapter_model_name(self.model_name, a) for a in resident
                ]
        return meta

    def _gen_args(self, params: dict) -> dict:
        prompt = self._require_prompt(params)
        messages, was_transcript = parse_transcript(prompt)
        if was_transcript:
            # flatten back to a plain prompt ending with the assistant cue;
            # a real chat template would need a real tokenizer, which a
            # zero-egress node may not have
            prompt = "\n".join(f"{m['role']}: {m['content']}" for m in messages)
            prompt += "\nassistant:"
        return {
            "prompt": prompt,
            "max_new_tokens": min(
                int(params.get("max_new_tokens", self.max_new_tokens)), self.max_new_tokens
            ),
            "temperature": float(params.get("temperature", 0.7)),
            "top_k": int(params.get("top_k", 0)),
            "top_p": float(params.get("top_p", 1.0)),
            "min_p": float(params.get("min_p", 0.0)),
            "repetition_penalty": float(params.get("repetition_penalty", 1.0)),
            "presence_penalty": float(params.get("presence_penalty", 0.0)),
            "frequency_penalty": float(params.get("frequency_penalty", 0.0)),
            # fairness identity (router/): keys the scheduler's WDRR queue
            "tenant": str(params.get("tenant") or "default"),
            # multi-adapter serving (adapters/): which pool adapter this
            # generation decodes under (None = base model). The engine
            # raises a typed UnknownAdapter for anything non-resident.
            "adapter": params.get("adapter") or None,
        }

    def execute(self, params: dict[str, Any]) -> dict[str, Any]:
        if self.engine is None:
            raise ServiceError("Model not loaded")
        t0 = time.time()
        stops = normalize_stops(params.get("stop"))
        if stops:
            # route through the streaming path: the engine early-exits at
            # the stop hit (generate_stream's close releases the row), so
            # a 2048-budget request stopping at token 10 neither computes
            # nor BILLS the ~2038 discarded tokens (OpenAI semantics)
            return self._execute_with_stops(params, stops, t0)
        args = self._gen_args(params)
        result = self.engine.generate(**args)
        text = scrub_stop_words(result.text)
        out = self.result_dict(text, result.new_tokens, t0, self.price_per_token)
        out["tokens_per_sec"] = result.tokens_per_sec
        out["ttft_ms"] = int(result.ttft_s * 1000)
        out["finish_reason"] = result.finish_reason
        out["prompt_tokens"] = result.prompt_tokens  # /v1 usage accounting
        # the per-request latency breakdown (queue_wait/prefill/ttft/
        # tokens_per_s/spec_acceptance): rides gen_success frames so the
        # requester sees where its latency went (ISSUE 5)
        out["timing"] = dict(result.timings)
        return out

    def _execute_with_stops(self, params: dict, stops: tuple, t0: float) -> dict:
        args = self._gen_args(params)
        acc, n_seen, hit, result = "", 0, False, None
        gen = self.engine.generate_stream(**args)
        try:
            for ev in gen:
                if ev.get("done"):
                    result = ev.get("result")
                    break
                acc += ev.get("text", "")
                n_seen += len(ev.get("tokens") or ([1] if ev.get("token") is not None else []))
                if stop_cut(acc, stops) is not None:
                    hit = True  # closing the generator cancels the row
                    break
        finally:
            gen.close()
        rc, sc = role_cut(acc), stop_cut(acc, stops)
        text = acc[:rc if sc is None else min(rc, sc)]
        n_tokens = result.new_tokens if result is not None else n_seen
        out = self.result_dict(text, n_tokens, t0, self.price_per_token)
        out["finish_reason"] = (
            "stop" if hit or (sc is not None and sc <= rc)
            else (result.finish_reason if result else "stop")
        )
        if result is not None:
            out["tokens_per_sec"] = result.tokens_per_sec
            out["ttft_ms"] = int(result.ttft_s * 1000)
            out["prompt_tokens"] = result.prompt_tokens
            out["timing"] = dict(result.timings)
        return out

    def execute_stream(self, params: dict[str, Any]) -> Iterator[str]:
        if self.engine is None:
            raise ServiceError("Model not loaded")
        stops = normalize_stops(params.get("stop"))
        args = self._gen_args(params)
        try:
            # scrub_stream_delta holds back chars so a stop marker split
            # across chunk boundaries never leaks its prefix (execute()
            # scrubs the full text; streaming must match it byte-for-byte)
            acc = ""  # full raw accumulation
            emitted = 0  # chars of scrub(acc) already yielded
            n_new = None  # real token count, when the engine reports it
            timing = None  # engine timing breakdown off the done event
            n_seen = 0  # tokens streamed so far (the billable count on a
            # stop hit — the engine's own total never arrives then)
            for ev in self.engine.generate_stream(**args):
                if ev.get("done"):  # flush the held-back tail
                    res = ev.get("result")
                    if res is not None:
                        n_new = res.new_tokens
                        timing = dict(res.timings)
                    tail = scrub_stop_words(acc, stops)
                    if tail[emitted:]:
                        yield self.stream_line({"text": tail[emitted:]})
                    break
                acc += ev.get("text", "")
                n_seen += len(ev.get("tokens") or ([1] if ev.get("token") is not None else []))
                delta, emitted, hit = scrub_stream_delta(acc, emitted, stops)
                if delta:
                    yield self.stream_line({"text": delta})
                if hit:
                    n_new = n_seen
                    break
            # the done line carries the node's REAL accounting so mesh
            # peers / the web gateway don't fall back to len/4 estimates
            done: dict[str, Any] = {"done": True}
            if n_new is not None:
                done["tokens"] = int(n_new)
                done["cost"] = self.price_per_token * int(n_new)
            if timing is not None:
                done["timing"] = timing
            yield self.stream_line(done)
        except Exception as e:  # match reference stream-error contract
            yield self.stream_line({"status": "error", "message": f"Stream error: {e}"})
