"""PipelineService: cross-peer pipeline serving behind the BaseService
contract.

BASELINE config 4 (a model split across peers) as a FIRST-CLASS mesh
service: a coordinator node part_loads stage workers
(meshnet/pipeline.PipelineCoordinator), then this wrapper exposes the
chained generation through the same execute/execute_stream contract
every other backend speaks — so a pipeline-split model is served
through the standard gateway, mesh routing, and streaming paths, not a
bespoke code path. (Reference contrast: the worker hops exist at
node.py:249-277 but nothing ever served them as a model.)

Threading: services run on executor threads (meshnet node / HTTP
gateway), while the coordinator speaks WebSockets on the node's asyncio
loop — execute() bridges with run_coroutine_threadsafe against the loop
captured at construction.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import time
from typing import Any, Iterator

from .base import (
    BaseService,
    ServiceError,
    parse_transcript,
    normalize_stops,
    scrub_stop_words,
    scrub_stream_delta,
)

REQUEST_TIMEOUT_S = 300.0


class PipelineService(BaseService):
    def __init__(
        self,
        coordinator,  # meshnet.pipeline.PipelineCoordinator (stages loaded)
        loop: asyncio.AbstractEventLoop,
        model_name: str,
        tokenizer=None,
        price_per_token: float = 0.0,
        max_new_tokens: int = 2048,
        max_batch: int = 8,
        # >1: that many free-running microbatch groups interleave their
        # chains across stages; "auto" resolves a depth from gossiped
        # stage timings vs hop RTTs on distinct hosts, 1 on a shared
        # host (meshnet.pipeline.resolve_microbatches)
        n_microbatches: int | str = "auto",
        # lets `--model auto` resolve the tokenizer/vocab + advertised
        # name from the checkpoint's own config
        checkpoint_path: str | None = None,
    ):
        super().__init__("pipeline")
        self.coordinator = coordinator
        # concurrent execute() calls ride one continuous-batching session:
        # n_stages wire hops per decode step for the whole batch, not per
        # request (meshnet/pipeline.PipelineSession)
        self.session = coordinator.session(
            max_batch=max_batch, n_microbatches=n_microbatches
        )
        self.loop = loop
        self.model_name = model_name
        if tokenizer is None or model_name in (None, "", "auto"):
            # resolve via the same any-checkpoint rule as the workers so
            # `serve-pipeline --model auto` gets the right vocab AND
            # advertises the resolved name (the coordinator keeps sending
            # the requested string; workers alias it — add_stage_runner)
            from ..engine.tokenizer import load_tokenizer
            from ..models.config import resolve_model_config

            cfg = resolve_model_config(model_name, checkpoint_path)
            if tokenizer is None:
                tokenizer = load_tokenizer(checkpoint_path, cfg.vocab_size)
            if model_name in (None, "", "auto"):
                self.model_name = cfg.name
        self.tokenizer = tokenizer
        self.price_per_token = price_per_token
        self.max_new_tokens = max_new_tokens

    def get_metadata(self) -> dict[str, Any]:
        return {
            "models": [self.model_name],
            "price_per_token": self.price_per_token,
            "max_new_tokens": self.max_new_tokens,
            "backend": "pipeline",
            "stages": len(self.coordinator.stage_peers),
        }

    def _gen_args(self, params: dict) -> tuple[list[int], dict]:
        prompt = self._require_prompt(params)
        messages, was_transcript = parse_transcript(prompt)
        if was_transcript:
            prompt = "\n".join(f"{m['role']}: {m['content']}" for m in messages)
            prompt += "\nassistant:"
        ids = self.tokenizer.encode(prompt)
        kw = {
            "max_new_tokens": min(
                int(params.get("max_new_tokens", self.max_new_tokens)),
                self.max_new_tokens,
            ),
            "temperature": float(params.get("temperature", 0.0)),
            "eos_token_id": self.tokenizer.eos_token_id,
        }
        return ids, kw

    def _run(self, coro):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout=REQUEST_TIMEOUT_S)
        except BaseException:
            # cancel the coroutine (a hung worker would otherwise keep the
            # request's KV-cache slots allocated on EVERY stage forever —
            # generate's finally releases them only if it gets to run)
            fut.cancel()
            raise

    def execute(self, params: dict[str, Any]) -> dict[str, Any]:
        t0 = time.time()
        ids, kw = self._gen_args(params)
        try:
            out_ids = self._run(self.session.generate(ids, **kw))
        except Exception as e:  # noqa: BLE001 — surface as a service error
            # keep the taxonomy visible (StageDead/StageTimeout/...): a
            # caller deciding whether to re-submit needs the class name
            raise ServiceError(
                f"pipeline generation failed: {type(e).__name__}: {e}"
            ) from e
        text = scrub_stop_words(
            self.tokenizer.decode(out_ids), normalize_stops(params.get("stop"))
        )
        return self.result_dict(text, len(out_ids), t0, self.price_per_token)

    async def execute_async(self, params: dict[str, Any]) -> dict[str, Any]:
        """Loop-native execute: the mesh node awaits this directly instead
        of parking an executor thread on _run() — N concurrent requests
        cost N coroutines, not N blocked threads, and all of them batch
        into the one PipelineSession."""
        t0 = time.time()
        ids, kw = self._gen_args(params)
        try:
            out_ids = await asyncio.wait_for(
                self.session.generate(ids, **kw), timeout=REQUEST_TIMEOUT_S
            )
        except Exception as e:  # noqa: BLE001 — surface as a service error
            raise ServiceError(
                f"pipeline generation failed: {type(e).__name__}: {e}"
            ) from e
        text = scrub_stop_words(
            self.tokenizer.decode(out_ids), normalize_stops(params.get("stop"))
        )
        return self.result_dict(text, len(out_ids), t0, self.price_per_token)

    async def execute_stream_async(self, params: dict[str, Any]):
        """Async-generator twin of execute_stream for loop-native callers."""
        stops = normalize_stops(params.get("stop"))
        ids, kw = self._gen_args(params)
        q: asyncio.Queue = asyncio.Queue()
        DONE = object()

        def on_token(tok: int):
            q.put_nowait(tok)  # session loop runs on this same event loop

        async def run():
            try:
                await self.session.generate(ids, on_token=on_token, **kw)
                q.put_nowait(DONE)
            except Exception as e:  # noqa: BLE001 — stream-error contract
                q.put_nowait(e)

        producer = asyncio.get_running_loop().create_task(run())
        out_ids: list[int] = []
        emitted = 0
        deadline = time.time() + REQUEST_TIMEOUT_S
        try:
            while True:
                try:
                    item = await asyncio.wait_for(
                        q.get(), timeout=max(0.1, deadline - time.time())
                    )
                except asyncio.TimeoutError:
                    yield self.stream_line(
                        {"status": "error", "message": "Stream error: pipeline timeout"}
                    )
                    return
                if item is DONE:
                    break
                if isinstance(item, Exception):
                    yield self.stream_line(
                        {"status": "error", "message": f"Stream error: {item}"}
                    )
                    return
                out_ids.append(item)
                acc = self.tokenizer.decode(out_ids).rstrip("�")
                delta, emitted, hit = scrub_stream_delta(acc, emitted, stops)
                if delta:
                    yield self.stream_line({"text": delta})
                if hit:
                    break
        finally:
            if not producer.done():
                producer.cancel()  # release the row on early exit
        tail = scrub_stop_words(self.tokenizer.decode(out_ids), stops)
        if tail[emitted:]:
            yield self.stream_line({"text": tail[emitted:]})
        yield self.stream_line({
            "done": True, "tokens": len(out_ids),
            "cost": self.price_per_token * len(out_ids),
        })

    def execute_stream(self, params: dict[str, Any]) -> Iterator[str]:
        """Thread-bridge over execute_stream_async: one streaming
        implementation, two call conventions — executor-thread callers
        pull each item off the loop via run_coroutine_threadsafe."""
        agen = self.execute_stream_async(params)
        try:
            while True:
                fut = asyncio.run_coroutine_threadsafe(agen.__anext__(), self.loop)
                try:
                    yield fut.result(timeout=REQUEST_TIMEOUT_S)
                except StopAsyncIteration:
                    return
                except concurrent.futures.TimeoutError:
                    fut.cancel()
                    yield self.stream_line(
                        {"status": "error", "message": "Stream error: pipeline timeout"}
                    )
                    return
        finally:
            # abandoned/errored consumer: close the generator ON THE LOOP
            # so its producer task is cancelled and the session row retires
            asyncio.run_coroutine_threadsafe(agen.aclose(), self.loop)
