"""PipelineService: cross-peer pipeline serving behind the BaseService
contract.

BASELINE config 4 (a model split across peers) as a FIRST-CLASS mesh
service: a coordinator node part_loads stage workers
(meshnet/pipeline.PipelineCoordinator), then this wrapper exposes the
chained generation through the same execute/execute_stream contract
every other backend speaks — so a pipeline-split model is served
through the standard gateway, mesh routing, and streaming paths, not a
bespoke code path. (Reference contrast: the worker hops exist at
node.py:249-277 but nothing ever served them as a model.)

Threading: services run on executor threads (meshnet node / HTTP
gateway), while the coordinator speaks WebSockets on the node's asyncio
loop — execute() bridges with run_coroutine_threadsafe against the loop
captured at construction.
"""

from __future__ import annotations

import asyncio
import queue
import time
from typing import Any, Iterator

from .base import (
    BaseService,
    ServiceError,
    parse_transcript,
    scrub_stop_words,
    scrub_stream_delta,
)

REQUEST_TIMEOUT_S = 300.0


class PipelineService(BaseService):
    def __init__(
        self,
        coordinator,  # meshnet.pipeline.PipelineCoordinator (stages loaded)
        loop: asyncio.AbstractEventLoop,
        model_name: str,
        tokenizer=None,
        price_per_token: float = 0.0,
        max_new_tokens: int = 2048,
    ):
        super().__init__("pipeline")
        self.coordinator = coordinator
        self.loop = loop
        self.model_name = model_name
        if tokenizer is None:
            from ..engine.tokenizer import load_tokenizer
            from ..models import get_config

            tokenizer = load_tokenizer(None, get_config(model_name).vocab_size)
        self.tokenizer = tokenizer
        self.price_per_token = price_per_token
        self.max_new_tokens = max_new_tokens

    def get_metadata(self) -> dict[str, Any]:
        return {
            "models": [self.model_name],
            "price_per_token": self.price_per_token,
            "max_new_tokens": self.max_new_tokens,
            "backend": "pipeline",
            "stages": len(self.coordinator.stage_peers),
        }

    def _gen_args(self, params: dict) -> tuple[list[int], dict]:
        prompt = self._require_prompt(params)
        messages, was_transcript = parse_transcript(prompt)
        if was_transcript:
            prompt = "\n".join(f"{m['role']}: {m['content']}" for m in messages)
            prompt += "\nassistant:"
        ids = self.tokenizer.encode(prompt)
        kw = {
            "max_new_tokens": min(
                int(params.get("max_new_tokens", self.max_new_tokens)),
                self.max_new_tokens,
            ),
            "temperature": float(params.get("temperature", 0.0)),
            "eos_token_id": self.tokenizer.eos_token_id,
        }
        return ids, kw

    def _run(self, coro):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout=REQUEST_TIMEOUT_S)
        except BaseException:
            # cancel the coroutine (a hung worker would otherwise keep the
            # request's KV-cache slots allocated on EVERY stage forever —
            # generate's finally releases them only if it gets to run)
            fut.cancel()
            raise

    def execute(self, params: dict[str, Any]) -> dict[str, Any]:
        t0 = time.time()
        ids, kw = self._gen_args(params)
        try:
            out_ids = self._run(self.coordinator.generate(ids, **kw))
        except Exception as e:  # noqa: BLE001 — surface as a service error
            raise ServiceError(f"pipeline generation failed: {e}") from e
        text = scrub_stop_words(self.tokenizer.decode(out_ids))
        return self.result_dict(text, len(out_ids), t0, self.price_per_token)

    def execute_stream(self, params: dict[str, Any]) -> Iterator[str]:
        ids, kw = self._gen_args(params)
        q: queue.Queue = queue.Queue()
        DONE = object()

        def on_token(tok: int):
            q.put(tok)

        async def run():
            try:
                await self.coordinator.generate(ids, on_token=on_token, **kw)
                q.put(DONE)
            except Exception as e:  # noqa: BLE001 — stream-error contract
                q.put(e)

        producer = asyncio.run_coroutine_threadsafe(run(), self.loop)
        out_ids: list[int] = []
        emitted = 0  # chars of scrub(acc) already yielded (see base helper)
        deadline = time.time() + REQUEST_TIMEOUT_S
        while True:
            try:
                item = q.get(timeout=max(0.1, deadline - time.time()))
            except queue.Empty:
                producer.cancel()  # release worker-side KV slots
                yield self.stream_line(
                    {"status": "error", "message": "Stream error: pipeline timeout"}
                )
                return
            if item is DONE:
                break
            if isinstance(item, Exception):
                yield self.stream_line(
                    {"status": "error", "message": f"Stream error: {item}"}
                )
                return
            out_ids.append(item)
            # cumulative decode keeps multi-byte tokens UTF-8-safe; the
            # shared holdback keeps streamed bytes identical to execute()'s
            # scrubbed full text (no role-marker prefix ever leaks)
            acc = self.tokenizer.decode(out_ids).rstrip("�")
            delta, emitted, hit = scrub_stream_delta(acc, emitted)
            if delta:
                yield self.stream_line({"text": delta})
            if hit:
                producer.cancel()  # the rest would be scrubbed anyway
                break
        tail = scrub_stop_words(self.tokenizer.decode(out_ids))
        if tail[emitted:]:
            yield self.stream_line({"text": tail[emitted:]})
        yield self.stream_line({"done": True})
