"""The service contract every backend implements.

Wire-compatible with the reference (`services.py:13-25`): `get_metadata()`
feeds hello/service_announce messages; `execute(params) -> result dict` with
keys text/tokens/latency_ms/price_per_token/cost (reference services.py:
101-113); `execute_stream(params)` yields JSON-lines `{"text": chunk}` then
`{"done": true}` (reference services.py:74-80).
"""

from __future__ import annotations

import json
import time
from typing import Any, Iterator

from ..metrics import get_registry

# every backend's execute() funnels through result_dict, so this one
# histogram covers service execute latency for tpu/ollama/remote/fake
# alike (streaming paths report their own done-line accounting)
_H_EXECUTE = get_registry().histogram(
    "service.execute_ms", "service execute() latency per request (ms)"
)


class ServiceError(Exception):
    pass


class BaseService:
    """A hostable inference backend."""

    def __init__(self, name: str):
        self.name = name

    def get_metadata(self) -> dict[str, Any]:
        return {}

    def execute(self, params: dict[str, Any]) -> dict[str, Any]:
        raise NotImplementedError

    def execute_stream(self, params: dict[str, Any]) -> Iterator[str]:
        raise NotImplementedError

    # -- shared helpers -------------------------------------------------------

    async def _execute_via_thread(self, params: dict[str, Any]) -> dict[str, Any]:
        """`execute` off the event loop: services whose execute() blocks on
        network/disk expose ``execute_async = _execute_via_thread`` and the
        async gateway (meshnet/node._execute_local) takes the loop-native
        path; sync callers keep calling execute() unchanged."""
        import asyncio

        return await asyncio.to_thread(self.execute, params)

    async def _stream_via_thread(self, params: dict[str, Any]):
        """Async-generator bridge over a blocking ``execute_stream``: the
        sync iterator runs in a worker thread and lines hop to the loop
        through a queue, so a slow backend never stalls other in-flight
        generations. A consumer that raises or abandons the generator sets
        ``cancelled``, and the pump stops pulling at the next line — the
        backend isn't left generating a full response nobody reads (same
        contract api.py's _stream_service pump keeps)."""
        import asyncio
        import contextvars
        import threading

        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        cancelled = threading.Event()

        def pump():
            try:
                for line in self.execute_stream(params):
                    if cancelled.is_set():
                        break
                    loop.call_soon_threadsafe(q.put_nowait, ("line", line))
                loop.call_soon_threadsafe(q.put_nowait, ("end", None))
            except BaseException as e:  # noqa: BLE001 — re-raised on the loop
                loop.call_soon_threadsafe(q.put_nowait, ("err", e))

        # copy_context so spans emitted inside the worker thread keep their
        # caller as parent (run_in_executor alone drops contextvars — the
        # same guard node._execute_local applies)
        ctx = contextvars.copy_context()
        fut = loop.run_in_executor(None, ctx.run, pump)
        try:
            while True:
                kind, val = await q.get()
                if kind == "line":
                    yield val
                elif kind == "err":
                    raise val
                else:
                    break
            await fut  # the end/err marker means pump already returned
        finally:
            # sync set (no await: this also runs under GeneratorExit) —
            # the thread exits at its next line boundary
            cancelled.set()

    @staticmethod
    def _require_prompt(params: dict) -> str:
        prompt = params.get("prompt")
        if not prompt:
            raise ServiceError("Missing prompt")
        return prompt

    @staticmethod
    def result_dict(text: str, new_tokens: int, t0: float, price_per_token: float) -> dict:
        """The reference's result schema (services.py:101-113)."""
        latency_ms = int((time.time() - t0) * 1000.0)
        _H_EXECUTE.observe(latency_ms)
        return {
            "text": text,
            "tokens": int(new_tokens),
            "latency_ms": latency_ms,
            "price_per_token": price_per_token,
            "cost": price_per_token * int(new_tokens),
        }

    @staticmethod
    def stream_line(obj: dict) -> str:
        return json.dumps(obj) + "\n"


def parse_transcript(prompt: str) -> tuple[list[dict], bool]:
    """Parse a `user:`/`assistant:` transcript into chat messages (the
    reference does this inside generation, hf.py:54-81; we keep it at the
    service boundary). Returns (messages, was_transcript)."""
    lines = prompt.splitlines()
    roles = ("user:", "assistant:", "system:")
    if not any(ln.strip().lower().startswith(roles) for ln in lines):
        return [{"role": "user", "content": prompt}], False
    messages: list[dict] = []
    cur_role, cur = None, []
    for ln in lines:
        low = ln.strip().lower()
        matched = next((r for r in roles if low.startswith(r)), None)
        if matched:
            if cur_role is not None:
                messages.append({"role": cur_role, "content": "\n".join(cur).strip()})
            cur_role = matched[:-1]
            cur = [ln.strip()[len(matched):].lstrip()]
        elif cur_role is not None:
            cur.append(ln)
    if cur_role is not None:
        messages.append({"role": cur_role, "content": "\n".join(cur).strip()})
    return messages, True


STOP_MARKERS = ("\nuser:", "\nassistant:", "\nsystem:", "user:", "assistant:")
# streaming must hold back this many chars: a marker may still complete
STOP_HOLDBACK = max(len(m) for m in STOP_MARKERS) - 1


def normalize_stops(stop) -> tuple:
    """A request's `stop` param (OpenAI: string or list of strings) →
    tuple of non-empty strings, capped at 4 like OpenAI. Malformed values
    (ints, dicts, ...) normalize to () — a bad param must not crash the
    request after the compute is spent."""
    if not stop:
        return ()
    if isinstance(stop, str):
        stop = [stop]
    if not isinstance(stop, (list, tuple)):
        return ()
    return tuple(s for s in stop if isinstance(s, str) and s)[:4]


def role_cut(text: str) -> int:
    """Cut position for hallucinated role markers (idx > 0 rule: a reply
    that IS a role line isn't deleted whole — reference hf.py:111-136)."""
    cut = len(text)
    for marker in STOP_MARKERS:
        idx = text.find(marker)
        if idx > 0:
            cut = min(cut, idx)
    return cut


def stop_cut(text: str, stops: tuple) -> int | None:
    """Earliest caller-stop position (OpenAI semantics: ANY position,
    including 0), or None when no stop matches."""
    best = None
    for stop in stops:
        idx = text.find(stop)
        if idx >= 0 and (best is None or idx < best):
            best = idx
    return best


def scrub_stop_words(text: str, stops: tuple = ()) -> str:
    """Cut generation at a role-marker or caller stop string, whichever
    comes first (role_cut / stop_cut hold the two rules)."""
    cut = role_cut(text)
    sc = stop_cut(text, stops)
    if sc is not None:
        cut = min(cut, sc)
    return text[:cut]


def stop_holdback(stops: tuple = ()) -> int:
    return max([STOP_HOLDBACK] + [len(s) - 1 for s in stops])


def scrub_stream_delta(
    acc_text: str, emitted: int, stops: tuple = ()
) -> tuple[str, int, bool]:
    """Streaming stop-scrub step over CUMULATIVE text: returns
    (delta_to_emit, new_emitted, marker_hit). Holds back enough chars
    that a marker or stop string split across chunk boundaries never
    leaks its prefix — the streamed bytes must equal what execute()'s
    full-text scrub produces. Shared by every streaming backend
    (tpu / pipeline)."""
    scrubbed = scrub_stop_words(acc_text, stops)
    if len(scrubbed) < len(acc_text):  # a marker completed: flush & stop
        return scrubbed[emitted:], len(scrubbed), True
    safe = max(emitted, len(scrubbed) - stop_holdback(stops))
    return scrubbed[emitted:safe], safe, False
