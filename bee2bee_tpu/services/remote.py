"""RemoteService: proxy the HF serverless Inference API (reference
services.py:247-308 — InferenceClient text_generation with char/4 token
estimates). Requires network + HUGGING_FACE_HUB_TOKEN; raises ServiceError
cleanly when offline."""

from __future__ import annotations

import os
import time
from typing import Any, Iterator

from .base import BaseService, ServiceError


class RemoteService(BaseService):
    def __init__(
        self,
        model_name: str,
        price_per_token: float = 0.0,
        max_new_tokens: int = 2048,
        token: str | None = None,
    ):
        super().__init__("hf_remote")
        self.model_name = model_name
        self.price_per_token = price_per_token
        self.max_new_tokens = max_new_tokens
        self.token = token or os.environ.get("HUGGING_FACE_HUB_TOKEN")
        self._client = None

    def _client_or_raise(self):
        if self._client is None:
            try:
                from huggingface_hub import InferenceClient

                self._client = InferenceClient(model=self.model_name, token=self.token)
            except Exception as e:
                raise ServiceError(f"huggingface_hub unavailable: {e}")
        return self._client

    def get_metadata(self) -> dict[str, Any]:
        return {
            "models": [self.model_name],
            "price_per_token": self.price_per_token,
            "max_new_tokens": self.max_new_tokens,
            "backend": "hf_remote",
        }

    def execute(self, params: dict[str, Any]) -> dict[str, Any]:
        prompt = self._require_prompt(params)
        t0 = time.time()
        try:
            text = self._client_or_raise().text_generation(
                prompt,
                max_new_tokens=int(params.get("max_new_tokens", self.max_new_tokens)),
                temperature=max(float(params.get("temperature", 0.7)), 1e-3),
            )
        except ServiceError:
            raise
        except Exception as e:
            raise ServiceError(f"remote inference failed: {e}")
        # reference's char/4 estimate (services.py:296) — the API doesn't
        # return token counts
        return self.result_dict(text, max(1, len(text) // 4), t0, self.price_per_token)

    def execute_stream(self, params: dict[str, Any]) -> Iterator[str]:
        prompt = self._require_prompt(params)
        try:
            stream = self._client_or_raise().text_generation(
                prompt,
                max_new_tokens=int(params.get("max_new_tokens", self.max_new_tokens)),
                temperature=max(float(params.get("temperature", 0.7)), 1e-3),
                stream=True,
            )
            for chunk in stream:
                piece = getattr(getattr(chunk, "token", None), "text", None) or (
                    chunk if isinstance(chunk, str) else ""
                )
                if piece:
                    yield self.stream_line({"text": piece})
            yield self.stream_line({"done": True})
        except Exception as e:
            yield self.stream_line({"status": "error", "message": f"Stream error: {e}"})
