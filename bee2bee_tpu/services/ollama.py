"""OllamaService: proxy a local Ollama daemon behind the service contract
(reference services.py:118-245 — model-tag fuzzy matching, /api/generate
non-stream + stream)."""

from __future__ import annotations

import json
import time
from typing import Any, Iterator

from .base import BaseService, ServiceError


class OllamaService(BaseService):
    def __init__(
        self,
        model_name: str,
        price_per_token: float = 0.0,
        host: str = "http://127.0.0.1:11434",
        max_new_tokens: int = 2048,
        timeout_s: float = 300.0,
    ):
        super().__init__("ollama")
        self.model_name = model_name
        self.price_per_token = price_per_token
        self.host = host.rstrip("/")
        self.max_new_tokens = max_new_tokens
        self.timeout_s = timeout_s
        self._resolved: str | None = None

    def get_metadata(self) -> dict[str, Any]:
        return {
            "models": [self.model_name],
            "price_per_token": self.price_per_token,
            "max_new_tokens": self.max_new_tokens,
            "backend": "ollama",
        }

    def _resolve_tag(self) -> str:
        """Fuzzy-match the configured model against installed tags (the
        reference's both-ways `in` match, services.py:136-151)."""
        if self._resolved:
            return self._resolved
        import requests

        try:
            r = requests.get(f"{self.host}/api/tags", timeout=5)
            r.raise_for_status()
            tags = [m.get("name", "") for m in r.json().get("models", [])]
        except Exception as e:
            raise ServiceError(f"ollama unreachable at {self.host}: {e}")
        want = self.model_name.lower()
        for tag in tags:
            if tag.lower() == want:
                self._resolved = tag
                return tag
        for tag in tags:
            t = tag.lower()
            if want in t or t.split(":")[0] in want:
                self._resolved = tag
                return tag
        raise ServiceError(f"model {self.model_name!r} not found in ollama (have: {tags})")

    def _payload(self, params: dict, stream: bool) -> dict:
        return {
            "model": self._resolve_tag(),
            "prompt": self._require_prompt(params),
            "stream": stream,
            "options": {
                "num_predict": int(params.get("max_new_tokens", self.max_new_tokens)),
                "temperature": float(params.get("temperature", 0.7)),
            },
        }

    def execute(self, params: dict[str, Any]) -> dict[str, Any]:
        import requests

        t0 = time.time()
        try:
            r = requests.post(
                f"{self.host}/api/generate",
                json=self._payload(params, stream=False),
                timeout=self.timeout_s,
            )
            r.raise_for_status()
            body = r.json()
        except ServiceError:
            raise
        except Exception as e:
            raise ServiceError(f"ollama generate failed: {e}")
        text = body.get("response", "")
        new_tokens = int(body.get("eval_count") or max(1, len(text) // 4))
        out = self.result_dict(text, new_tokens, t0, self.price_per_token)
        if body.get("total_duration"):
            out["latency_ms"] = int(body["total_duration"] / 1e6)  # ns → ms
        return out

    # Loop-native variants: every OllamaService call blocks on a local-HTTP
    # round trip (tag resolution + /api/generate), so serving it under the
    # async gateway must offload to a worker thread — these wrappers are
    # what meshnet/node._execute_local picks up; sync callers are unchanged
    # (meshlint ML-A001 bug class: one blocking call stalls every in-flight
    # generation on the node's loop).
    execute_async = BaseService._execute_via_thread
    execute_stream_async = BaseService._stream_via_thread

    def execute_stream(self, params: dict[str, Any]) -> Iterator[str]:
        import requests

        try:
            r = requests.post(
                f"{self.host}/api/generate",
                json=self._payload(params, stream=True),
                stream=True,
                timeout=self.timeout_s,
            )
            r.raise_for_status()
            for line in r.iter_lines():
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if obj.get("response"):
                    yield self.stream_line({"text": obj["response"]})
                if obj.get("done"):
                    break
            yield self.stream_line({"done": True})
        except Exception as e:
            yield self.stream_line({"status": "error", "message": f"Stream error: {e}"})
