"""Service layer: the `BaseService` contract (metadata / execute /
execute_stream) with four backends — TPU engine, Ollama proxy, remote HF
Inference API, and a fake for tests (reference services.py:13-25 defines the
contract; the fake is the test backend SURVEY §4 says the reference lacks).
"""

from .base import BaseService, ServiceError  # noqa: F401
from .fake import FakeService  # noqa: F401


def __getattr__(name):
    # TPUService pulls in jax; OllamaService/RemoteService pull in requests.
    # Lazy so `import bee2bee_tpu.services` works in minimal contexts.
    if name == "TPUService":
        from .tpu import TPUService

        return TPUService
    if name == "OllamaService":
        from .ollama import OllamaService

        return OllamaService
    if name == "RemoteService":
        from .remote import RemoteService

        return RemoteService
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
