"""Web gateway: the SaaS HTTP facade over the mesh bridge.

Route-for-route parity with the reference's Express gateway
(/root/reference/app/api/index.js:16-216 — behavior studied, rebuilt on
aiohttp):

- ``POST /api/p2p/register``  — join-link registration → bridge retarget
- ``POST /api/p2p/generate``  — streamed generation (chunked text body),
  token metrics recorded after the stream (len/4 estimate, the
  reference's accounting) to the in-memory counters and, when configured,
  the Supabase registry's ``messages`` table via RegistryClient
- ``GET|POST /api/p2p/status`` — bridge stats + known mesh peers +
  optional direct node probe (``?node=http://host:port``)
- ``GET|POST /api/p2p/global_metrics`` — read/accumulate token totals
- ``GET /`` — the static browser UI (web/static/index.html): landing,
  one-click register, chat — the React SPA's three views without a JS
  build chain
"""

from __future__ import annotations

import asyncio
import json
import logging
from pathlib import Path

from aiohttp import web

from ..protocol import copy_sampling
from ..utils import pump_queue_until
from .bridge import MeshBridge

logger = logging.getLogger("bee2bee_tpu.web.gateway")

STATIC_DIR = Path(__file__).parent / "static"


def create_web_app(bridge: MeshBridge, registry=None) -> web.Application:
    app = web.Application()
    app["bridge"] = bridge
    app["registry"] = registry
    app["metrics"] = {"messages": 0, "tokens": 0, "cost": 0.0}

    async def register(request: web.Request):
        body = await request.json()
        link = body.get("link")
        if not link:
            return web.json_response({"error": "Missing join link"}, status=400)
        try:
            result = await bridge.register_join_link(link)
        except Exception as e:  # noqa: BLE001 — surface as the reference does
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({**result, **bridge.stats(), "mode": "bee2bee-tpu"})

    async def generate(request: web.Request):
        body = await request.json()
        task = body.get("task") or {}
        prompt = task.get("prompt") or body.get("prompt")
        model = task.get("model") or body.get("model") or "default"
        target = task.get("targetNode") or body.get("targetNode")
        if not prompt:
            return web.json_response({"error": "Prompt is required"}, status=400)

        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            }
        )
        await resp.prepare(request)
        loop = request.app["metrics"]
        chunk_q: asyncio.Queue = asyncio.Queue()

        # on_chunk fires on this same event loop (bridge reader / direct
        # HTTP stream), so put_nowait is safe; the pump below forwards each
        # chunk to the client AS IT ARRIVES — real streaming, not buffer-
        # then-flush
        def on_chunk(text: str):
            chunk_q.put_nowait(text)

        payload = {
            "prompt": prompt,
            "model": model,
            "max_new_tokens": body.get("max_new_tokens") or body.get("max_tokens"),
            "temperature": body.get("temperature"),
        }
        # sampling knobs ride the payload into BOTH bridge paths (direct
        # HTTP posts the payload verbatim; the WS dialect copies from the
        # same list again) — the top level wins over the legacy task{}
        copy_sampling(task, payload)
        copy_sampling(body, payload)
        req_task = asyncio.create_task(bridge.request(
            payload,
            on_chunk=on_chunk,
            target=target,
        ))
        streamed = ""

        async def emit(piece: str):
            nonlocal streamed
            streamed += piece
            await resp.write(piece.encode())

        try:
            result = await pump_queue_until(req_task, chunk_q, emit)
            text = result.get("text") or streamed
            if len(text) > len(streamed):  # non-streamed remainder
                await resp.write(text[len(streamed):].encode())
            if body.get("meta") or task.get("meta"):
                # opt-in response metadata trailer, mirroring the existing
                # "\n\n[Error]: " in-stream convention (the raw-text stream
                # has nowhere else to carry it): the node's per-request
                # timing breakdown reaches gateway clients end-to-end
                trailer = {
                    "tokens": result.get("tokens"),
                    "cost": result.get("cost"),
                    "latency_ms": result.get("latency_ms"),
                    "timing": result.get("timing"),
                }
                await resp.write(
                    ("\n\n[Meta]: " + json.dumps(trailer)).encode()
                )
            # prefer the node's REAL accounting when the mesh result
            # carries it (services/base.py result_dict: tokens + cost =
            # price_per_token x tokens); len/4 is the reference's estimate,
            # kept only as the streamed-remainder fallback
            tokens = result.get("tokens") or max(1, len(text) // 4)
            cost = float(result.get("cost") or 0.0)
            user_id = body.get("user_id") or (task.get("user_id") if task else None)
            loop["messages"] += 1
            loop["tokens"] += tokens
            loop["cost"] += cost
            registry = request.app["registry"]
            if registry is not None and getattr(registry, "enabled", False):
                try:
                    await registry.record_message(
                        node_id=target or "GLOBAL_METRICS", tokens=tokens,
                        cost=cost, user_id=user_id,
                    )
                except Exception:  # noqa: BLE001 — metrics never break serving
                    logger.debug("registry metrics write failed", exc_info=True)
        except Exception as e:  # noqa: BLE001 — pump_queue_until already
            # cancelled and consumed req_task on any failure
            await resp.write(f"\n\n[Error]: {e}".encode())
        await resp.write_eof()
        return resp

    async def status(request: web.Request):
        out = {
            "bridge": bridge.stats(),
            "mesh": [
                {"addr": addr, **{k: v for k, v in meta.items() if k != "services"},
                 "models": sorted(
                     m for svc in (meta.get("services") or {}).values()
                     for m in (svc.get("models") or [])
                 )}
                for addr, meta in bridge.peer_metadata.items()
            ],
            "metrics": request.app["metrics"],
        }
        node = request.query.get("node")
        if node:  # optional direct probe of a node's own HTTP gateway
            import aiohttp

            try:
                async with aiohttp.ClientSession() as s:
                    async with s.get(
                        f"{node.rstrip('/')}/", timeout=aiohttp.ClientTimeout(total=5)
                    ) as r:
                        out["probe"] = await r.json()
            except Exception as e:  # noqa: BLE001
                out["probe"] = {"error": str(e)}
        return web.json_response(out)

    async def global_metrics(request: web.Request):
        metrics = request.app["metrics"]
        if request.method == "POST":
            body = await request.json()
            metrics["tokens"] += int(body.get("tokens") or 0)
            metrics["cost"] += float(body.get("cost") or 0.0)
            metrics["messages"] += 1
        return web.json_response(
            {**metrics, "total_requests": bridge.total_requests,
             "bridge_tokens": bridge.total_tokens}
        )

    async def index(request: web.Request):
        return web.FileResponse(STATIC_DIR / "index.html")

    app.router.add_post("/api/p2p/register", register)
    app.router.add_post("/api/p2p/generate", generate)
    app.router.add_route("*", "/api/p2p/status", status)
    app.router.add_route("*", "/api/p2p/global_metrics", global_metrics)
    app.router.add_get("/", index)
    # the component kit + any other static assets (web/static/ui.js)
    app.router.add_static("/static/", STATIC_DIR)
    return app


async def start_web_gateway(
    bridge: MeshBridge, host: str = "0.0.0.0", port: int = 4001, registry=None
):
    app = create_web_app(bridge, registry=registry)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    logger.info("web gateway on http://%s:%s", host, port)
    return runner
