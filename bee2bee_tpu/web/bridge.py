"""MeshBridge: the browser-facing bridge into the WS mesh.

Speaks the exact dialect the reference's Node bridge speaks against a mesh
node (/root/reference/app/api/bridge.js — studied for behavior, rebuilt in
asyncio):

- correlates replies by ``task_id`` (falling back to ``rid``) — the node
  side answers either key;
- ``gen_chunk`` text accumulates per request with a live on_chunk callback;
  ``gen_success`` resolves with the final text (or the joined chunks);
  ``gen_error`` rejects;
- ``hello`` captures peer metadata (api host/port, services, metrics) used
  for the direct-HTTP fast path and the status endpoint;
- answers ``ping`` with ``pong`` so the node keeps the link healthy;
- reconnects 5 s after a drop, forever (bridge.js behavior);
- request timeout 90 s with PARTIAL-RESULT SALVAGE: accumulated chunks
  resolve rather than erroring (bridge.js:333-344);
- direct-HTTP-first fast path: when the target node advertises an api
  port, POST its gateway ``/generate`` and relay the JSON-lines stream,
  falling back to the WS path (bridge.js:272-309).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import time

try:
    import websockets
except ImportError:  # gate the missing dep: loopback shim (wscompat.py)
    from .. import wscompat as websockets

from .. import protocol
from ..joinlink import parse_join_link
from ..utils import TaskTracker, new_id

logger = logging.getLogger("bee2bee_tpu.web.bridge")

RECONNECT_S = 5.0
REQUEST_TIMEOUT_S = 90.0
MAX_FRAME = protocol.MAX_FRAME  # one constant governs both ends


class MeshBridge:
    def __init__(self, seeds: list[str] | None = None, region: str = "global"):
        self.seeds = list(seeds or [])
        self.region = region
        self.registered_node: str | None = None  # priority target (join link)
        self.active_ws = None
        self.active_url: str | None = None
        self.peer_metadata: dict[str, dict] = {}  # ws addr -> hello payload
        self.pending: dict[str, dict] = {}
        self.total_requests = 0
        self.total_tokens = 0
        self._tasks = TaskTracker("bridge")  # logs crashes, cancelled on stop
        self._reader_task: asyncio.Task | None = None
        self._reconnect_task: asyncio.Task | None = None
        self._stopped = False

    # ------------------------------------------------------------ lifecycle

    async def start(self):
        await self.connect()
        return self

    async def stop(self):
        self._stopped = True
        await self._tasks.cancel_all()
        ws, self.active_ws = self.active_ws, None
        if ws is not None:
            with contextlib.suppress(Exception):
                await ws.close()
        self.active_url = None
        for req in self.pending.values():
            if not req["fut"].done():
                req["fut"].set_exception(RuntimeError("bridge stopped"))
        self.pending.clear()  # meshlint: ignore[ML-R003] -- rid-keyed futures map: request/_reader touch only their own rid; stop sweeps after cancel_all

    async def connect(self) -> bool:
        """Dial the registered node first, then the seeds, keeping the
        first that answers."""
        candidates = ([self.registered_node] if self.registered_node else []) + [
            s for s in self.seeds if s != self.registered_node
        ]
        for url in candidates:
            ws = None
            try:
                ws = await asyncio.wait_for(
                    websockets.connect(url, max_size=MAX_FRAME), timeout=10
                )
                # announce ourselves so the node says hello back with metadata
                await ws.send(protocol.encode(
                    protocol.msg(protocol.HELLO, peer_id=new_id("bridge"),
                                 region=self.region, services={})
                ))
            except Exception as e:  # noqa: BLE001 — try the next candidate;
                # a half-open socket must not become active_ws (it would wedge
                # every later request with no reader and no reconnect)
                logger.debug("bridge dial %s failed: %s", url, e)
                if ws is not None:
                    with contextlib.suppress(Exception):
                        await ws.close()
                continue
            self.active_ws, self.active_url = ws, url
            if self._reader_task:
                self._reader_task.cancel()
            self._reader_task = self._tasks.spawn(self._reader(ws))
            logger.info("bridge connected to %s", url)
            return True
        return False

    def _schedule_reconnect(self):
        if self._stopped or (self._reconnect_task and not self._reconnect_task.done()):
            return

        async def later():
            await asyncio.sleep(RECONNECT_S)
            if not self._stopped and self.active_ws is None:
                await self.connect()

        self._reconnect_task = self._tasks.spawn(later())

    # ------------------------------------------------------------ dialect

    async def _reader(self, ws):
        try:
            async for raw in ws:
                if isinstance(raw, bytes):
                    continue  # binary piece/tensor frames are node-to-node
                try:
                    msg = json.loads(raw)
                except ValueError:
                    continue
                await self._on_message(ws, msg)
        except websockets.ConnectionClosed:
            pass
        finally:
            if self.active_ws is ws:
                self.active_ws = None
                self.active_url = None
                logger.warning("bridge connection closed; retrying in %ss", RECONNECT_S)
                self._schedule_reconnect()

    async def _on_message(self, ws, msg: dict):
        tid = msg.get("task_id") or msg.get("rid")
        req = self.pending.get(tid) if tid else None
        mtype = msg.get("type")

        if mtype in ("hello", "handshake"):
            if self.active_url:
                meta = dict(self.peer_metadata.get(self.active_url) or {})
                meta.update(msg)
                meta["last_seen"] = time.time()
                self.peer_metadata[self.active_url] = meta
            return
        if mtype in ("gen_chunk", "chunk"):
            if req is not None:
                text = msg.get("text") or ""
                req["chunks"].append(text)
                if req.get("on_chunk"):
                    req["on_chunk"](text)
            return
        if mtype in ("gen_success", "gen_response", "gen_result"):
            if req is not None and not req["fut"].done():
                self.pending.pop(tid, None)
                if msg.get("error"):  # gen_result doubles as the relay's
                    # error carrier (consensus_deadlock / relay_link_failure)
                    req["fut"].set_exception(RuntimeError(msg["error"]))
                else:
                    req["fut"].set_result(
                        {
                            "text": msg.get("text") or "".join(req["chunks"]),
                            "rid": tid,
                            "latency_ms": int((time.time() - req["start"]) * 1000),
                            "backend": msg.get("backend"),
                            # real accounting when the node reports it
                            # (services' done line → gen_success fields)
                            "tokens": msg.get("tokens"),
                            "cost": msg.get("cost"),
                            # per-request latency breakdown (ISSUE 5):
                            # queue_wait/prefill/ttft/tokens_per_s from the
                            # serving engine, forwarded hop-by-hop
                            "timing": msg.get("timing"),
                        }
                    )
            return
        if mtype == "gen_error":
            if req is not None and not req["fut"].done():
                self.pending.pop(tid, None)
                req["fut"].set_exception(
                    RuntimeError(msg.get("error") or "node failure")
                )
            return
        if mtype == "ping":
            # echo ts: the node's pong handler only refreshes rtt/health
            # when the timestamp comes back (meshnet/node.py _handle_pong)
            with contextlib.suppress(Exception):
                await ws.send(protocol.encode(
                    protocol.msg(protocol.PONG, ts=msg.get("ts"))
                ))

    # ------------------------------------------------------------ requests

    async def register_join_link(self, link: str) -> dict:
        """Point the bridge at a specific node via its deep link."""
        info = parse_join_link(link)
        node_id, addrs = info["node_id"], info["bootstrap_addrs"]
        if not addrs:
            raise ValueError("join link carries no addresses")
        self.registered_node = addrs[0]
        # claim-then-close: null the attr BEFORE the await so a reconnect
        # landing during close() can't be clobbered (ML-R001 window)
        stale, self.active_ws = self.active_ws, None
        if stale is not None:
            with contextlib.suppress(Exception):
                await stale.close()
        ok = await self.connect()
        return {"ok": ok, "node_id": node_id, "addr": addrs[0]}

    def _direct_target(self, target: str | None) -> str | None:
        """http://host:api_port for the fast path, from hello metadata."""
        meta = None
        if target:
            meta = self.peer_metadata.get(target)
        elif self.active_url:
            meta = self.peer_metadata.get(self.active_url)
        if not meta:
            return None
        host, port = meta.get("api_host"), meta.get("api_port")
        return f"http://{host}:{port}" if host and port else None

    async def _request_direct(self, base: str, payload: dict, on_chunk) -> dict:
        import aiohttp

        t0 = time.time()
        chunks: list[str] = []
        final: dict = {}
        async with aiohttp.ClientSession() as session:
            async with session.post(
                f"{base}/generate",
                json={**payload, "stream": True},
                timeout=aiohttp.ClientTimeout(total=REQUEST_TIMEOUT_S),
            ) as resp:
                resp.raise_for_status()
                async for line in resp.content:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        continue
                    if obj.get("status") == "error":
                        raise RuntimeError(obj.get("message") or "stream error")
                    text = obj.get("text") or ""
                    if text:
                        chunks.append(text)
                        if on_chunk:
                            on_chunk(text)
                    if obj.get("done"):
                        if obj.get("tokens") is not None:
                            final["tokens"] = int(obj["tokens"])
                            final["cost"] = float(obj.get("cost") or 0.0)
                        if obj.get("timing") is not None:
                            final["timing"] = obj["timing"]
                        break
        return {
            "text": "".join(chunks),
            "latency_ms": int((time.time() - t0) * 1000),
            "via": "direct",
            "tokens": final.get("tokens"),
            "cost": final.get("cost"),
            "timing": final.get("timing"),
        }

    async def request(
        self,
        payload: dict,
        on_chunk=None,
        target: str | None = None,
        timeout: float = REQUEST_TIMEOUT_S,
    ) -> dict:
        """Generate via the mesh: direct HTTP to the target node's gateway
        when its api port is known, else the WS dialect."""
        self.total_requests += 1
        base = self._direct_target(target)
        if base:
            try:
                result = await self._request_direct(base, payload, on_chunk)
                self.total_tokens += result.get("tokens") or max(1, len(result["text"]) // 4)
                return result
            except Exception as e:  # noqa: BLE001 — WS fallback
                logger.info("direct path to %s failed (%s); using WS", base, e)

        if self.active_ws is None and not await self.connect():
            raise RuntimeError("mesh unreachable: no node accepted a connection")
        task_id = new_id("task")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        req = {"fut": fut, "chunks": [], "on_chunk": on_chunk, "start": time.time()}
        self.pending[task_id] = req
        try:
            await self._send_gen_request(task_id, payload)
        except Exception:
            self.pending.pop(task_id, None)  # never leak the entry
            raise
        try:
            result = await asyncio.wait_for(fut, timeout=timeout)
        except asyncio.TimeoutError:
            if req["chunks"]:  # partial salvage (bridge.js:333-344)
                result = {"text": "".join(req["chunks"]), "rid": task_id, "partial": True}
            else:
                raise TimeoutError("node timeout: no output before deadline")
        finally:
            # also covers cancellation (the gateway cancels this coroutine
            # when the browser hangs up): the entry must never outlive the
            # request, or pending grows forever under client churn
            self.pending.pop(task_id, None)
        self.total_tokens += result.get("tokens") or max(1, len(result["text"]) // 4)
        return result

    async def _send_gen_request(self, task_id: str, payload: dict):
        frame = {
            "type": protocol.GEN_REQUEST,
            "task_id": task_id,
            "model": payload.get("model"),
            "prompt": payload.get("prompt"),
            "max_new_tokens": payload.get("max_new_tokens") or payload.get("max_tokens"),
            "temperature": payload.get("temperature"),
            "stream": True,
        }
        # every hop copies the knobs from ONE list (protocol.SAMPLING_KEYS):
        # this hop used to drop them all — top_p/penalties/stop sent through
        # the browser gateway silently became defaults (meshlint ML-F004)
        protocol.copy_sampling(payload, frame)
        await self.active_ws.send(protocol.encode(frame))

    # ------------------------------------------------------------ status

    def stats(self) -> dict:
        return {
            "connected": self.active_ws is not None,
            "active_node": self.active_url,
            "registered_node": self.registered_node,
            "seeds": self.seeds,
            "known_peers": len(self.peer_metadata),
            "pending": len(self.pending),
            "total_requests": self.total_requests,
            "total_tokens": self.total_tokens,
            "region": self.region,
        }
