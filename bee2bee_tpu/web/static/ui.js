/* bee2bee-tpu UI component kit (dependency-free).
 *
 * The reference ships a shadcn-style kit (app/src/components/ui/
 * badge|button|card|input|globe — behavior studied); this is the same
 * layer for the no-build static tier: DOM factories + the markdown
 * renderer, consumed by index.html. Everything renders through esc()
 * first — model output can never inject HTML. */
'use strict';

const B2B = (() => {
  /* ------------------------------- primitives ------------------------ */
  function el(tag, attrs = {}, ...children) {
    const node = document.createElement(tag);
    for (const [k, v] of Object.entries(attrs)) {
      if (k === 'class') node.className = v;
      else if (k.startsWith('on')) node[k] = v;
      else node.setAttribute(k, v);
    }
    for (const c of children)
      node.append(typeof c === 'string' ? document.createTextNode(c) : c);
    return node;
  }

  const badge = (text, tone = '') => el('span', {class: `b2b-badge ${tone}`}, text);
  const button = (label, onclick, attrs = {}) =>
    el('button', {class: 'b2b-btn', onclick, ...attrs}, label);
  const input = (attrs = {}) => el('input', {class: 'b2b-input', ...attrs});
  const card = (title, ...children) =>
    el('div', {class: 'b2b-card'},
       ...(title ? [el('div', {class: 'b2b-card-title'}, title)] : []),
       ...children);
  const statTile = (label, valueId) =>
    el('div', {class: 'tile'},
       el('div', {class: 'v', id: valueId}, '—'),
       el('div', {class: 'l'}, label));

  /* --------------------------- markdown renderer --------------------- */
  function esc(s) {
    return s.replace(/&/g,'&amp;').replace(/</g,'&lt;').replace(/>/g,'&gt;')
            .replace(/"/g,'&quot;').replace(/'/g,'&#39;');
  }
  function unesc(s) {  // exact inverse of esc(); &amp; LAST
    return s.replace(/&lt;/g,'<').replace(/&gt;/g,'>')
            .replace(/&quot;/g,'"').replace(/&#39;/g,"'").replace(/&amp;/g,'&');
  }
  function hiCode(code, lang) {
    let h = esc(code);
    if (/^(py|python|js|javascript|ts|c|cpp|java|go|rust|sh|bash)/.test(lang||'')) {
      h = h.replace(/(#[^\n]*|\/\/[^\n]*)/g, '<span class="c">$1</span>')
           .replace(/(&quot;[^&]*?&quot;|'[^'\n]*'|"[^"\n]*")/g, '<span class="s">$1</span>')
           .replace(/\b(def|class|return|import|from|if|elif|else|for|while|in|not|and|or|try|except|finally|with|as|lambda|yield|await|async|const|let|var|function|new|this|fn|pub|struct|impl|match)\b/g,
                    '<span class="k">$1</span>')
           .replace(/\b(\d+\.?\d*)\b/g, '<span class="n">$1</span>');
    }
    return h;
  }
  function mdInline(s) {
    return s
      .replace(/`([^`]+)`/g, (_, c) => '<code>' + c + '</code>')
      .replace(/\*\*([^*]+)\*\*/g, '<strong>$1</strong>')
      .replace(/(^|\W)\*([^*\n]+)\*(?=\W|$)/g, '$1<em>$2</em>')
      .replace(/\[([^\]]+)\]\((https?:[^)\s"'`&<>]+)\)/g,
               '<a href="$2" target="_blank" rel="noopener">$1</a>');
  }
  function renderMd(src) {
    const lines = esc(src).split('\n');
    const out = [];
    let i = 0, para = [];
    const flush = () => { if (para.length) { out.push('<p>'+mdInline(para.join('<br>'))+'</p>'); para = []; } };
    while (i < lines.length) {
      const L = lines[i];
      const fence = L.match(/^```(\w*)\s*$/);
      if (fence) {                                   // fenced code block
        flush();
        const lang = fence[1]; const buf = [];
        for (i++; i < lines.length && !/^```\s*$/.test(lines[i]); i++) buf.push(lines[i]);
        i++;  // closing fence
        out.push('<pre><code>' + hiCode(unesc(buf.join('\n')), lang) + '</code></pre>');
        continue;
      }
      const h = L.match(/^(#{1,3})\s+(.*)$/);
      if (h) { flush(); out.push(`<h${h[1].length}>`+mdInline(h[2])+`</h${h[1].length}>`); i++; continue; }
      if (/^\s*([-*])\s+/.test(L)) {                 // unordered list
        flush(); const items = [];
        while (i < lines.length && /^\s*([-*])\s+/.test(lines[i]))
          items.push('<li>'+mdInline(lines[i].replace(/^\s*[-*]\s+/,''))+'</li>'), i++;
        out.push('<ul>'+items.join('')+'</ul>'); continue;
      }
      if (/^\s*\d+\.\s+/.test(L)) {                  // ordered list
        flush(); const items = [];
        while (i < lines.length && /^\s*\d+\.\s+/.test(lines[i]))
          items.push('<li>'+mdInline(lines[i].replace(/^\s*\d+\.\s+/,''))+'</li>'), i++;
        out.push('<ol>'+items.join('')+'</ol>'); continue;
      }
      if (/^&gt;\s?/.test(L)) {                      // blockquote
        flush(); const buf = [];
        while (i < lines.length && /^&gt;\s?/.test(lines[i]))
          buf.push(lines[i].replace(/^&gt;\s?/,'')), i++;
        out.push('<blockquote>'+mdInline(buf.join('<br>'))+'</blockquote>'); continue;
      }
      if (!L.trim()) { flush(); i++; continue; }
      para.push(L); i++;
    }
    flush();
    return out.join('\n');
  }

  /* ----------------------------- chat components --------------------- */
  function messageBubble(role, text) {
    const div = el('div', {class: 'msg ' + role});
    if (role === 'assistant') div.innerHTML = renderMd(text);
    else div.textContent = text;
    return div;
  }
  function metaLine(text) {
    return el('div', {class: 'meta'}, text);
  }

  return {el, badge, button, input, card, statTile,
          esc, unesc, hiCode, mdInline, renderMd,
          messageBubble, metaLine};
})();
