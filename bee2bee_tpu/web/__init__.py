"""L5 web layer: the mesh-facing SaaS gateway + bridge.

The reference ships this tier as Node.js (Express gateway
/root/reference/app/api/index.js:16-216, WS bridge app/api/bridge.js:8-426,
React SPA app/src/App.jsx) against a Supabase registry. This package is the
same capability re-built in the framework's own stack — an aiohttp gateway
and an asyncio bridge speaking the identical WebSocket dialect (task_id
correlation, gen_chunk/gen_success accumulation, ping→pong, hello metadata
capture, 5 s reconnect, 90 s timeout with partial salvage, direct-HTTP
fast path) plus a static browser chat/register UI. Zero Node.js required.
"""

from .bridge import MeshBridge  # noqa: F401
from .gateway import create_web_app, start_web_gateway  # noqa: F401
