-- Registry schema for the bee2bee-tpu web tier (Supabase/Postgres).
-- Capability parity with the reference's SUPABASE_SCHEMA.sql (profiles,
-- messages token accounting, node_logs telemetry, system_stats view,
-- active_nodes mesh discovery — reference :10-101), tightened where the
-- build plan prescribes (SURVEY §7 "what NOT to carry over"): profile
-- writes require a session and messages/node_logs are insert-only, unlike
-- the reference's blanket-open policies (:83-96). active_nodes stays
-- anon-writable — see the RLS note below for why, and its cost.

create table if not exists profiles (
  -- id mirrors auth.users.id (Supabase convention) — own_profile RLS
  -- below compares it to auth.uid(), so the default must match
  id uuid primary key default auth.uid(),
  handle text unique,
  created_at timestamptz not null default now()
);

-- per-generation token + cost accounting (gateway writes after each
-- stream). user_id ties spend to an authenticated profile; cost is the
-- node-computed price_per_token x tokens that rides each mesh result
-- (services/base.py result_dict — reference :10-20 carries the same pair)
create table if not exists messages (
  id bigint generated always as identity primary key,
  node_id text not null,
  user_id uuid references profiles (id),
  role text not null default 'assistant',
  content text,
  tokens integer not null default 0,
  cost double precision not null default 0,
  created_at timestamptz not null default now()
);
create index if not exists messages_node_created on messages (node_id, created_at);
create index if not exists messages_user on messages (user_id, created_at);

-- auth hook: a signup creates its profile row automatically (reference
-- :41-52) — the gateway can then attribute messages.user_id immediately
create or replace function public.handle_new_user()
returns trigger language plpgsql security definer set search_path = public as $$
begin
  insert into public.profiles (id, handle)
  values (new.id, coalesce(new.raw_user_meta_data->>'handle', new.email))
  on conflict (id) do nothing;
  return new;
end; $$;
drop trigger if exists on_auth_user_created on auth.users;
create trigger on_auth_user_created
  after insert on auth.users
  for each row execute function public.handle_new_user();

-- raw node telemetry (optional; the mesh itself carries metrics on pings)
create table if not exists node_logs (
  id bigint generated always as identity primary key,
  node_id text not null,
  metrics jsonb not null default '{}'::jsonb,
  created_at timestamptz not null default now()
);

-- mesh discovery: one row per live node, upserted by RegistryClient
create table if not exists active_nodes (
  node_id text primary key,
  address text not null,
  region text,
  models jsonb not null default '[]'::jsonb,
  metrics jsonb not null default '{}'::jsonb,
  api_port integer,
  last_seen timestamptz not null default now()
);
create index if not exists active_nodes_last_seen on active_nodes (last_seen);

-- aggregate view the gateway's global_metrics can read
create or replace view system_stats as
select
  count(*) filter (where last_seen > now() - interval '5 minutes') as live_nodes,
  (select coalesce(sum(tokens), 0) from messages)                  as total_tokens,
  (select coalesce(sum(cost), 0)   from messages)                  as total_cost,
  (select count(*) from messages)                                  as total_messages
from active_nodes;

-- RLS: reads are public (discovery must work anonymously). Mesh telemetry
-- writes (active_nodes upserts, messages/node_logs inserts) are open to
-- the anon role because that is the credential RegistryClient ships with
-- (nodes register with SUPABASE_ANON_KEY — same operational model as the
-- reference). BE AWARE what that means: the refresh_nodes policy below
-- necessarily permits anon UPDATE of ANY active_nodes row (RLS cannot
-- scope a policy to "the upsert conflict path only"), so any holder of
-- the anon key can rewrite another node's advertised address — the same
-- registry-poisoning exposure the reference has. The rendezvous registry
-- is a discovery hint, not an authority: nodes verify peers by the mesh
-- handshake, and piece payloads are content-hash verified regardless of
-- who advertised them. A private mesh removes the exposure by swapping
-- upsert_nodes/refresh_nodes for service-role checks (RegistryClient
-- then ships the service key). Tightened vs the reference (:83-96):
-- messages/node_logs are insert-only and profile writes need a session.
alter table profiles     enable row level security;
alter table messages     enable row level security;
alter table node_logs    enable row level security;
alter table active_nodes enable row level security;

create policy read_nodes    on active_nodes for select using (true);
create policy read_stats    on messages     for select using (true);
create policy upsert_nodes  on active_nodes for insert with check (true);
create policy refresh_nodes on active_nodes for update
  using (true) with check (true);  -- upsert's conflict path
create policy write_message on messages     for insert with check (true);
create policy write_logs    on node_logs    for insert with check (true);
-- profiles.id follows the Supabase convention of mirroring auth.users.id,
-- so ownership is the id itself — a session can only touch its own row
create policy own_profile   on profiles     for all
  using (auth.uid() = id) with check (auth.uid() = id);

-- stale-node pruning (run via pg_cron; the reference documents a manual
-- DELETE with a 1 h window, :99-101)
-- select cron.schedule('prune-nodes', '*/15 * * * *',
--   $$delete from active_nodes where last_seen < now() - interval '1 hour'$$);
