"""Tunnel helpers: expose a mesh node from behind NAT via a public tunnel.

The reference's cloud-node story is four Colab notebooks that shell out to
ngrok/bore/cloudflared and paste the public address into a join link
(/root/reference/notebook/ConnectIT_Cloud_Node.ipynb and the -BORE/-NGROK
variants — behavior studied). This module is the reusable core those
notebooks lacked: detect an available tunnel binary, open a TCP tunnel to
the node's WS port, parse the public address from the provider's output,
and rewrite the node's join link/announce address to the tunneled endpoint.

Design notes:
- Pure parser functions per provider (parse_bore_line / parse_ngrok_api /
  parse_cloudflared_line) so the address extraction is testable without
  the binaries or network; the process plumbing is a thin shell on top.
- The "stub" provider returns a fixed public address without spawning
  anything — tests and the notebook's dry-run path use it.
- Tunnels carry raw TCP (the mesh speaks ws:// over it). cloudflared's
  quick tunnels are HTTPS-only, so its URL maps to wss://; bore/ngrok
  map to ws://host:port.

CLI: ``--tunnel bore|ngrok|cloudflared|stub|auto`` on the serve commands
(bee2bee_tpu/__main__.py) wires this into run_p2p_node; docs recipe in
docs/CLOUD_NODE.md; notebook in notebook/cloud_node.ipynb.
"""

from __future__ import annotations

import json
import logging
import re
import shutil
import subprocess
import threading
import time
import urllib.request
from dataclasses import dataclass, field

logger = logging.getLogger("bee2bee_tpu.tunnel")

PROVIDERS = ("bore", "ngrok", "cloudflared")
DEFAULT_BORE_SERVER = "bore.pub"
NGROK_API = "http://127.0.0.1:4040/api/tunnels"


# ------------------------------------------------------------- pure parsers


def parse_bore_line(line: str, server: str = DEFAULT_BORE_SERVER) -> str | None:
    """bore prints ``listening at bore.pub:35735`` (also via its log line
    ``remote_port=35735``). Returns ``ws://host:port`` or None."""
    m = re.search(r"listening at ([\w.\-]+):(\d+)", line)
    if m:
        return f"ws://{m.group(1)}:{m.group(2)}"
    m = re.search(r"remote_port[=:]\s*(\d+)", line)
    if m:
        return f"ws://{server}:{m.group(1)}"
    return None


def parse_cloudflared_line(line: str) -> str | None:
    """cloudflared quick tunnels print ``https://<name>.trycloudflare.com``
    (TLS-terminated → the mesh dials it as wss://)."""
    m = re.search(r"https://([\w\-]+\.trycloudflare\.com)", line)
    if m:
        return f"wss://{m.group(1)}"
    return None


def parse_ngrok_api(payload: str | dict, local_port: int) -> str | None:
    """The ngrok agent's local API lists tunnels; pick the TCP tunnel that
    fronts our port. ``tcp://0.tcp.ngrok.io:NNNN`` → ``ws://...``."""
    data = json.loads(payload) if isinstance(payload, str) else payload
    for t in data.get("tunnels", []):
        addr = t.get("config", {}).get("addr", "")
        if addr.endswith(f":{local_port}") and t.get("public_url", "").startswith("tcp://"):
            host_port = t["public_url"][len("tcp://"):]
            return f"ws://{host_port}"
    return None


# --------------------------------------------------------------- processes


@dataclass
class Tunnel:
    provider: str
    local_port: int
    ws_url: str  # public address the mesh can dial
    proc: subprocess.Popen | None = None
    _log_tail: list[str] = field(default_factory=list)

    def close(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        self.proc = None

    @property
    def host(self) -> str:
        return self.ws_url.split("://", 1)[1].rsplit(":", 1)[0]

    @property
    def port(self) -> int:
        tail = self.ws_url.split("://", 1)[1]
        if ":" in tail:
            return int(tail.rsplit(":", 1)[1])
        return 443 if self.ws_url.startswith("wss") else 80


def detect_providers() -> list[str]:
    """Tunnel binaries present on PATH, in preference order."""
    return [p for p in PROVIDERS if shutil.which(p)]


def _pump_lines(proc: subprocess.Popen, sink: list[str], parse, found: list):
    """Reader thread: collect output lines, stop parsing once found."""
    for raw in iter(proc.stdout.readline, b""):
        line = raw.decode("utf-8", "replace").rstrip()
        sink.append(line)
        if len(sink) > 50:
            del sink[:-50]
        if not found:
            url = parse(line)
            if url:
                found.append(url)


def open_tunnel(
    local_port: int,
    provider: str = "auto",
    timeout: float = 30.0,
    bore_server: str = DEFAULT_BORE_SERVER,
) -> Tunnel:
    """Spawn a tunnel for ``local_port`` and wait for its public address.

    ``stub`` never spawns anything (tests / dry runs). ``auto`` picks the
    first binary found on PATH. Raises RuntimeError when no provider is
    available or the address never appears within ``timeout``."""
    if provider == "stub":
        return Tunnel("stub", local_port, f"ws://stub.tunnel.invalid:{local_port}")
    if provider == "auto":
        avail = detect_providers()
        if not avail:
            raise RuntimeError(
                "no tunnel binary found (install one of: bore, ngrok, "
                "cloudflared) — or pass --tunnel stub for a dry run"
            )
        provider = avail[0]

    if provider == "bore":
        cmd = ["bore", "local", str(local_port), "--to", bore_server]
        parse = lambda line: parse_bore_line(line, bore_server)  # noqa: E731
    elif provider == "cloudflared":
        # quick tunnels proxy HTTP(S) origins only — which is exactly what
        # the node's WS listener is (WebSocket = HTTP upgrade); a tcp://
        # origin would need an authenticated tunnel + client-side
        # `cloudflared access` and would make the wss address undialable
        cmd = ["cloudflared", "tunnel", "--url", f"http://127.0.0.1:{local_port}"]
        parse = parse_cloudflared_line
    elif provider == "ngrok":
        cmd = ["ngrok", "tcp", str(local_port), "--log", "stdout"]
        parse = lambda line: None  # noqa: E731 — ngrok's address comes from its API
    else:
        raise ValueError(f"unknown tunnel provider {provider!r}")

    if shutil.which(cmd[0]) is None:
        raise RuntimeError(f"{cmd[0]} not found on PATH")
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        start_new_session=True,  # our SIGINT must not kill the tunnel
    )
    tail: list[str] = []
    found: list[str] = []
    threading.Thread(
        target=_pump_lines, args=(proc, tail, parse, found), daemon=True
    ).start()

    deadline = time.time() + timeout
    while time.time() < deadline:
        if found:
            return Tunnel(provider, local_port, found[0], proc, tail)
        if provider == "ngrok":  # poll the agent's local API
            try:
                with urllib.request.urlopen(NGROK_API, timeout=2) as r:
                    url = parse_ngrok_api(r.read().decode(), local_port)
                if url:
                    return Tunnel(provider, local_port, url, proc, tail)
            except Exception:  # noqa: BLE001 — agent not up yet
                pass
        if proc.poll() is not None:
            break
        time.sleep(0.3)
    proc.terminate()
    raise RuntimeError(
        f"{provider} tunnel did not yield a public address in {timeout:.0f}s; "
        f"last output: {tail[-3:]}"
    )


async def open_tunnel_async(
    local_port: int,
    provider: str = "auto",
    timeout: float = 30.0,
    bore_server: str = DEFAULT_BORE_SERVER,
) -> Tunnel:
    """Async front for :func:`open_tunnel`, whose polling core sleeps and
    does sync HTTP (the ngrok agent probe) — that must never run on the
    node's event loop (meshlint ML-A001 bug class), so it runs in a worker
    thread. run_p2p_node boots tunnels through this."""
    import asyncio

    return await asyncio.to_thread(
        open_tunnel, local_port, provider=provider,
        timeout=timeout, bore_server=bore_server,
    )


def apply_to_node(node, tunnel: Tunnel) -> str:
    """Point the node's announce address at the tunnel and return the
    tunneled join link (what a remote peer actually dials). A wss tunnel
    (cloudflared terminates TLS) must announce wss:// — P2PNode.addr
    would otherwise advertise plaintext ws:// into a TLS endpoint."""
    node.announce_host = tunnel.host
    node.announce_port = tunnel.port
    node.announce_scheme = tunnel.ws_url.split("://", 1)[0]
    return node.join_link()
