"""STUN client: public endpoint discovery + NAT-type classification.

Capability parity with the reference's ``bee2bee/stun_client.py`` (RFC
5389-style binding request/response, XOR-MAPPED-ADDRESS decode, parallel
multi-server query, NAT-type detection via two-server consistency —
reference stun_client.py:10-180), rebuilt as a pure codec + thin socket
layer so every parsing path is unit-testable against a fake loopback
server instead of the real Internet (the reference's tests hit live STUN
servers with vacuous asserts, reference tests/test_nat_optional.py:1-14).

TPU-relevant because mesh peers behind NAT must learn an announceable
address before they can serve; datacenter TPU hosts usually have public
IPs, so everything here degrades to a no-op gracefully.
"""

from __future__ import annotations

import os
import secrets
import socket
import struct
import time as _time
from concurrent.futures import ThreadPoolExecutor, as_completed
from dataclasses import dataclass

MAGIC_COOKIE = 0x2112A442
BINDING_REQUEST = 0x0001
BINDING_SUCCESS = 0x0101
ATTR_MAPPED_ADDRESS = 0x0001
ATTR_XOR_MAPPED_ADDRESS = 0x0020
_FAMILY_IPV4 = 0x01

# Well-known public servers; override with BEE2BEE_STUN_SERVERS="host:port,..."
DEFAULT_SERVERS: tuple[tuple[str, int], ...] = (
    ("stun.l.google.com", 19302),
    ("stun1.l.google.com", 19302),
    ("stun2.l.google.com", 19302),
    ("stun.cloudflare.com", 3478),
    ("stun.ekiga.net", 3478),
    ("stun.stunprotocol.org", 3478),
    ("stun.voipstunt.com", 3478),
)


def _servers_from_env() -> tuple[tuple[str, int], ...]:
    raw = os.environ.get("BEE2BEE_STUN_SERVERS", "")
    if not raw:
        return DEFAULT_SERVERS
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.partition(":")
        out.append((host, int(port or 3478)))
    return tuple(out) or DEFAULT_SERVERS


@dataclass(frozen=True)
class StunResult:
    """Public endpoint as seen by one STUN server."""

    ip: str
    port: int
    server: str


def build_binding_request(txn_id: bytes | None = None) -> tuple[bytes, bytes]:
    """Return (packet, transaction_id) for an RFC5389 binding request."""
    txn_id = txn_id or secrets.token_bytes(12)
    if len(txn_id) != 12:
        raise ValueError("transaction id must be 12 bytes")
    header = struct.pack("!HHI", BINDING_REQUEST, 0, MAGIC_COOKIE) + txn_id
    return header, txn_id


def parse_binding_response(data: bytes, txn_id: bytes) -> tuple[str, int] | None:
    """Decode (ip, port) from a binding success response, else None.

    Prefers XOR-MAPPED-ADDRESS; falls back to plain MAPPED-ADDRESS.
    """
    if len(data) < 20:
        return None
    msg_type, msg_len, cookie = struct.unpack("!HHI", data[:8])
    if msg_type != BINDING_SUCCESS or cookie != MAGIC_COOKIE:
        return None
    if data[8:20] != txn_id:
        return None
    body = data[20 : 20 + msg_len]
    plain: tuple[str, int] | None = None
    off = 0
    while off + 4 <= len(body):
        attr_type, attr_len = struct.unpack("!HH", body[off : off + 4])
        val = body[off + 4 : off + 4 + attr_len]
        off += 4 + attr_len + ((4 - attr_len % 4) % 4)  # values pad to 32 bits
        if len(val) < 8 or val[1] != _FAMILY_IPV4:
            continue
        port = struct.unpack("!H", val[2:4])[0]
        addr = struct.unpack("!I", val[4:8])[0]
        if attr_type == ATTR_XOR_MAPPED_ADDRESS:
            port ^= MAGIC_COOKIE >> 16
            addr ^= MAGIC_COOKIE
            return socket.inet_ntoa(struct.pack("!I", addr)), port
        if attr_type == ATTR_MAPPED_ADDRESS:
            plain = socket.inet_ntoa(struct.pack("!I", addr)), port
    return plain


def build_binding_response(
    txn_id: bytes, ip: str, port: int, xor: bool = True
) -> bytes:
    """Encode a binding success response — used by tests' fake server and
    by any peer acting as a rendezvous helper."""
    addr = struct.unpack("!I", socket.inet_aton(ip))[0]
    if xor:
        attr_type = ATTR_XOR_MAPPED_ADDRESS
        port_enc = port ^ (MAGIC_COOKIE >> 16)
        addr_enc = addr ^ MAGIC_COOKIE
    else:
        attr_type = ATTR_MAPPED_ADDRESS
        port_enc, addr_enc = port, addr
    attr = struct.pack("!HHBBHI", attr_type, 8, 0, _FAMILY_IPV4, port_enc, addr_enc)
    return struct.pack("!HHI", BINDING_SUCCESS, len(attr), MAGIC_COOKIE) + txn_id + attr


class STUNClient:
    """Query STUN servers for the public (ip, port) of this host."""

    def __init__(
        self,
        servers: tuple[tuple[str, int], ...] | None = None,
        timeout: float = 2.0,
        source_port: int = 0,
    ):
        self.servers = servers if servers is not None else _servers_from_env()
        self.timeout = timeout
        self.source_port = source_port

    def query_server(
        self, host: str, port: int, sock: socket.socket | None = None
    ) -> StunResult | None:
        """One binding round-trip against a single server.

        Pass an existing bound socket to reuse one local port across
        queries — required for NAT-type comparison, where the NAT mapping
        is keyed by the source port.
        """
        packet, txn_id = build_binding_request()
        own_sock = sock is None
        if own_sock:
            sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            sock.bind(("0.0.0.0", self.source_port))
        try:
            sock.settimeout(self.timeout)
            sock.sendto(packet, (host, port))
            # drain until our transaction id answers (a reused socket may
            # still hold late replies from a previous query)
            deadline = _time.monotonic() + self.timeout
            while True:
                data, _ = sock.recvfrom(2048)
                decoded = parse_binding_response(data, txn_id)
                if decoded is not None:
                    return StunResult(
                        ip=decoded[0], port=decoded[1], server=f"{host}:{port}"
                    )
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return None
                sock.settimeout(remaining)
        except OSError:
            return None
        finally:
            if own_sock:
                sock.close()

    def get_public_endpoint(self, max_servers: int = 4) -> StunResult | None:
        """Query several servers in parallel; first success returns without
        waiting for the slow/unreachable ones (their threads die on their
        own socket timeouts)."""
        targets = list(self.servers[:max_servers])
        if not targets:
            return None
        pool = ThreadPoolExecutor(max_workers=len(targets))
        try:
            futures = [pool.submit(self.query_server, h, p) for h, p in targets]
            for fut in as_completed(futures):
                res = fut.result()
                if res is not None:
                    return res
            return None
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    def detect_nat_type(self) -> str:
        """Classify NAT by consistency of mappings across two servers.

        Returns one of: "blocked", "open", "cone", "symmetric", "unknown".
        Both binding requests leave from ONE local socket, so the NAT holds
        a single mapping for them: same (ip, port) seen by two distinct
        servers → endpoint-independent mapping ("cone"); differing ports →
        "symmetric"; mapping equals a local interface address → "open".
        """
        results: list[StunResult] = []
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            sock.bind(("0.0.0.0", self.source_port))
            for host, port in self.servers:
                res = self.query_server(host, port, sock=sock)
                if res is not None and all(r.server != res.server for r in results):
                    results.append(res)
                if len(results) >= 2:
                    break
        except OSError:
            pass
        finally:
            sock.close()
        if not results:
            return "blocked"
        local_ips = _local_addresses()
        if results[0].ip in local_ips:
            return "open"
        if len(results) < 2:
            return "unknown"
        a, b = results[0], results[1]
        if (a.ip, a.port) == (b.ip, b.port):
            return "cone"
        return "symmetric"


def _local_addresses() -> set[str]:
    addrs = {"127.0.0.1"}
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None, socket.AF_INET):
            addrs.add(info[4][0])
    except OSError:
        pass
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        addrs.add(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    return addrs
