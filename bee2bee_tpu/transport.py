"""The Transport seam: one dial/serve/send/close contract, three backends.

Historically `meshnet/node.py` imported `websockets` directly (falling
back to the `wscompat` loopback shim when the package is absent), which
welded the mesh to real sockets: no way to run 200 nodes in-process with
deterministic delivery, injected latency, loss, or partitions. This
module narrows everything the mesh uses into a `Transport` interface and
re-homes both existing paths behind it:

- `WebsocketsTransport` — the real `websockets` package (RFC 6455, TLS,
  wire compatibility with the reference's JS bridge).
- `LoopbackTransport` — the `wscompat` shim (plain asyncio streams with
  private length-prefixed framing; tests and single-host dev meshes).
- `simnet.SimTransport` — the in-process virtual network (seeded
  delivery order, per-link latency/loss, partitionable regions).

The contract is the narrow slice of the websockets API the codebase
actually exercises (wscompat's module docstring enumerates it):

- `await transport.serve(handler, host, port, max_size=...)` → server
  handle with `.sockets`, `.close()` (listener AND established
  connections), `await .wait_closed()`.
- `await transport.dial(addr, max_size=..., open_timeout=...)` →
  connection with `await .send(str|bytes)`, `await .recv()`,
  `await .close()`, async iteration ending on any close.
- `transport.exceptions.ConnectionClosed` family for except clauses.

Backends are free to expose richer objects (the real package's protocol
instances pass through untouched); the mesh only relies on the slice
above.
"""

from __future__ import annotations

from typing import Any


class Transport:
    """Transport interface. `exceptions` must expose a ConnectionClosed
    attribute usable in except clauses; `dial`/`serve` follow the
    websockets `connect`/`serve` shapes documented above."""

    #: exception namespace; backends override with their own family
    exceptions: Any = None

    #: human tag for logs / bench stamps
    name = "abstract"

    async def dial(self, addr: str, *, max_size: int | None = None,
                   open_timeout: float = 10) -> Any:
        raise NotImplementedError

    async def serve(self, handler, host: str, port: int, *,
                    max_size: int | None = None) -> Any:
        raise NotImplementedError


class WebsocketsTransport(Transport):
    """Real `websockets` package. Constructed lazily so importing this
    module never requires the dependency."""

    name = "websockets"

    def __init__(self):
        import websockets  # noqa: F401 — hard dependency of this backend

        self._ws = websockets
        self.exceptions = websockets.exceptions

    async def dial(self, addr: str, *, max_size: int | None = None,
                   open_timeout: float = 10):
        return await self._ws.connect(
            addr, max_size=max_size, open_timeout=open_timeout
        )

    async def serve(self, handler, host: str, port: int, *,
                    max_size: int | None = None):
        return await self._ws.serve(handler, host, port, max_size=max_size)


class LoopbackTransport(Transport):
    """The wscompat shim as a Transport: plain asyncio streams, private
    framing, ws:// only. Both ends of a link must use it — exactly the
    tests / single-host-dev situation it exists for."""

    name = "loopback"

    def __init__(self):
        from . import wscompat

        self._ws = wscompat
        self.exceptions = wscompat.exceptions

    async def dial(self, addr: str, *, max_size: int | None = None,
                   open_timeout: float = 10):
        return await self._ws.connect(
            addr, max_size=max_size, open_timeout=open_timeout
        )

    async def serve(self, handler, host: str, port: int, *,
                    max_size: int | None = None):
        return await self._ws.serve(handler, host, port, max_size=max_size)


_DEFAULT: Transport | None = None


def default_transport() -> Transport:
    """The process-default transport: real websockets when the package is
    importable, else the loopback shim — the same fallback the mesh has
    always had, now expressed as backend selection. Cached: both backends
    are stateless dial/serve factories."""
    global _DEFAULT
    if _DEFAULT is None:
        try:
            _DEFAULT = WebsocketsTransport()
        except ImportError:
            _DEFAULT = LoopbackTransport()
    return _DEFAULT


def resolve_transport(transport: Transport | None) -> Transport:
    """Standard `transport=` ctor-argument resolution: explicit wins,
    None means the process default."""
    return transport if transport is not None else default_transport()
