"""Training: a mesh-sharded causal-LM train step.

The reference's distributed training is a toy: per-layer forward/backward of
a numpy MLP shipped as JSON floats over WebSocket (reference node.py:99-182,
model.py:7-71). The TPU-native realization is one jit-compiled train step
over a `jax.sharding.Mesh` — gradients ride XLA collectives (psum over
`data`, reduce-scatter under TP) instead of JSON frames, and the same
partition rules that drive serving (models/partition.py) drive the
optimizer state.

Sharding model:
- params/opt state: partition_specs (TP on `model`, EP on `expert`)
- batch: tokens [B, T] sharded ('data', 'seq') — data parallel over `data`,
  sequence parallel over `seq` (XLA inserts the attention collectives; the
  dedicated ring-attention path lives in parallel/ring.py)
- remat: `jax.checkpoint` around each scanned layer body trades FLOPs for
  HBM (cfg.remat)
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import core
from ..models.config import ModelConfig
from ..models.partition import partition_specs, shard_params


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    beta1: float = 0.9
    beta2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 0
    total_steps: int = 0  # >0 enables cosine decay after warmup
    remat: bool = False
    param_dtype: str = "float32"  # master params; compute casts per model
    # ZeRO-1 / cross-replica weight-update sharding (the "Automatic
    # Cross-Replica Sharding of Weight Update in Data-Parallel Training"
    # recipe, done the XLA way): Adam moments shard over the `data` axis
    # instead of replicating — a constraint on the optimizer state is all
    # it takes, the partitioner inserts the reduce-scatter/all-gather.
    # Saves ~2x params of HBM per replica at data-parallel degree N.
    zero1: bool = False


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any


def make_optimizer(tcfg: TrainConfig) -> optax.GradientTransformation:
    if tcfg.total_steps > 0:
        sched = optax.warmup_cosine_decay_schedule(
            0.0, tcfg.learning_rate, max(tcfg.warmup_steps, 1), tcfg.total_steps
        )
    elif tcfg.warmup_steps > 0:
        sched = optax.linear_schedule(0.0, tcfg.learning_rate, tcfg.warmup_steps)
    else:
        sched = tcfg.learning_rate
    return optax.chain(
        optax.clip_by_global_norm(tcfg.grad_clip),
        optax.adamw(
            sched, b1=tcfg.beta1, b2=tcfg.beta2, weight_decay=tcfg.weight_decay
        ),
    )


def xent_loss_metrics(logits, ids, loss_mask=None):
    """Shifted next-token cross entropy + metrics — the ONE place the
    loss/metrics contract lives (the dense and ring-SP steps both call it)."""
    logits = logits[:, :-1, :]
    targets = ids[:, 1:]
    mask = (
        jnp.ones_like(targets, jnp.float32)
        if loss_mask is None
        else loss_mask[:, 1:].astype(jnp.float32)
    )
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    acc = ((jnp.argmax(logits, axis=-1) == targets) * mask).sum() / denom
    return loss, {"loss": loss, "accuracy": acc, "tokens": denom}


def loss_fn(params, cfg: ModelConfig, batch: dict, remat: bool = False):
    """Next-token cross entropy. batch: input_ids [B, T] (+ optional
    loss_mask [B, T] over the *target* positions)."""
    ids = batch["input_ids"]
    logits, _ = core.forward(params, cfg, ids, None, jnp.int32(0), remat=remat)
    return xent_loss_metrics(logits, ids, batch.get("loss_mask"))


def widen_spec(spec: P, shape, n: int) -> P:
    """Add `data` to the first divisible, currently-unsharded dim — THE
    zero1 widening rule, shared by init and checkpoint restore (a desync
    would make a --zero1 resume reshard or OOM)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, (e, d) in enumerate(zip(entries, shape)):
        if e is None and d % n == 0 and d >= n:
            entries[i] = "data"
            break
    return P(*entries)


def opt_partition_specs(params, opt_shape, mesh: Mesh, zero1: bool):
    """PartitionSpec tree for the optimizer state: each param-shaped leaf
    inherits its param's spec (keypath-suffix matching — same-shaped
    params can carry opposite TP axes), degraded to replicated when the
    dims don't divide the mesh (shard_params' own fallback), and widened
    over `data` when zero1. Scalars (step counts) stay replicated."""
    from jax.tree_util import keystr, tree_flatten_with_path, tree_map_with_path

    from ..models.partition import _fits

    specs = partition_specs(params)
    param_paths = {
        keystr(path): spec
        for (path, _), spec in zip(
            tree_flatten_with_path(params)[0],
            jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P)),
        )
    }
    n = mesh.shape.get("data", 1) if zero1 else 1

    def build(path, leaf):
        ps = keystr(path)
        spec = next((s for pp, s in param_paths.items() if ps.endswith(pp)), P())
        if not _fits(leaf, spec, mesh):
            spec = P()
        if n > 1 and leaf.ndim >= 1:
            spec = widen_spec(spec, leaf.shape, n)
        return spec

    return tree_map_with_path(build, opt_shape)


def make_train_state(
    cfg: ModelConfig,
    tcfg: TrainConfig,
    key=None,
    params=None,
    mesh: Mesh | None = None,
) -> TrainState:
    if params is None:
        if key is None:
            key = jax.random.key(0)
        params = core.init_params(cfg, key, dtype=jnp.dtype(tcfg.param_dtype))
    if mesh is not None:
        params = shard_params(params, mesh)
    opt = make_optimizer(tcfg)
    if tcfg.zero1 and mesh is not None and mesh.shape.get("data", 1) > 1:
        # moments are BORN data-sharded (jit init with out_shardings): an
        # eager init would transiently allocate the replicated footprint —
        # the exact allocation zero1 exists to avoid
        opt_shape = jax.eval_shape(opt.init, params)
        specs = opt_partition_specs(params, opt_shape, mesh, zero1=True)
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        opt_state = jax.jit(opt.init, out_shardings=shardings)(params)
        n_sharded = sum(
            1 for s in jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
            if "data" in tuple(s)
        )
        if n_sharded == 0:
            import logging

            logging.getLogger("bee2bee_tpu.train").warning(
                "zero1 requested but no optimizer leaf dim divides the data "
                "axis (%d): moments stay replicated, no HBM saved",
                mesh.shape.get("data", 1),
            )
    else:
        # moments inherit the param shardings by structure (same shapes)
        opt_state = opt.init(params)
    return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=opt_state)


def make_step_from_loss(
    loss, tcfg: TrainConfig, batch_sharding=None, donate=True, opt_sharding=None
):
    """Shared step body: loss(params, batch) -> (loss, metrics) becomes a
    jitted (state, batch) -> (state, metrics) with optimizer update,
    grad_norm, optional batch sharding constraint, and state donation.

    opt_sharding: a sharding pytree matching opt_state — the ZeRO-1 path
    constrains the UPDATED optimizer state to it so the data-axis shard
    survives every step (unconstrained propagation may silently follow
    the replicated grads instead)."""
    opt = make_optimizer(tcfg)

    def step(state: TrainState, batch: dict):
        if batch_sharding is not None:
            batch = {
                k: jax.lax.with_sharding_constraint(v, batch_sharding)
                for k, v in batch.items()
            }
        (loss_val, metrics), grads = jax.value_and_grad(loss, has_aux=True)(
            state.params, batch
        )
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        if opt_sharding is not None:
            opt_state = jax.lax.with_sharding_constraint(opt_state, opt_sharding)
        params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return (
            TrainState(step=state.step + 1, params=params, opt_state=opt_state),
            metrics,
        )

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_train_step(
    cfg: ModelConfig, tcfg: TrainConfig, mesh: Mesh | None = None, opt_sharding=None
):
    """Returns jitted (state, batch) -> (state, metrics).

    With a mesh: the batch is constrained to ('data','seq') over (B, T) so
    DP/SP are explicit, and donation keeps params/opt state in place in HBM.
    """
    batch_sharding = (
        NamedSharding(mesh, P("data", "seq")) if mesh is not None else None
    )
    return make_step_from_loss(
        lambda params, batch: loss_fn(params, cfg, batch, tcfg.remat),
        tcfg,
        batch_sharding,
        opt_sharding=opt_sharding,
    )


def globalize_batch(batch: dict, mesh: Mesh | None) -> dict:
    """Multi-process: every host loads the SAME global batch (same
    corpus + shuffle seed) and materializes its addressable shards —
    jit under jax.distributed only accepts process-spanning inputs
    built this way. Sharding-driven (make_array_from_callback), so it
    stays correct even when the mesh's data axis does not span the
    processes (pure-TP meshes replicate the batch). Shared by Trainer
    and LoraTrainer — a trainer that skips this crashes on the first
    multi-host step."""
    if mesh is None or jax.process_count() == 1:
        return batch
    import numpy as np

    from ..parallel.multihost import global_array

    out = {}
    for k, v in batch.items():
        arr = np.asarray(v)
        spec = P("data", "seq") if arr.ndim >= 2 else P("data")
        out[k] = global_array(arr, mesh, spec)
    return out


class Trainer:
    """Stateful convenience wrapper: holds TrainState, steps on batches.

    Mirrors what a reference coordinator would orchestrate over WS workers
    (reference node.py:48-182) as a single SPMD program.
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig | None = None,
        mesh: Mesh | None = None,
        params=None,
        seed: int = 0,
    ):
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg or TrainConfig()
        self.mesh = mesh
        self.state = make_train_state(
            model_cfg, self.train_cfg, jax.random.key(seed), params=params, mesh=mesh
        )
        opt_sharding = None
        if self.train_cfg.zero1 and mesh is not None and mesh.shape.get("data", 1) > 1:
            # the REAL placed state carries the widened (data-sharded)
            # shardings — constrain the step to keep them
            opt_sharding = jax.tree.map(lambda x: x.sharding, self.state.opt_state)
        self._step = make_train_step(
            model_cfg, self.train_cfg, mesh, opt_sharding=opt_sharding
        )

    def _globalize(self, batch: dict) -> dict:
        return globalize_batch(batch, self.mesh)

    def train_step(self, batch: dict) -> dict:
        self.state, metrics = self._step(self.state, self._globalize(batch))
        return {k: float(v) for k, v in metrics.items()}

    @property
    def step(self) -> int:
        return int(self.state.step)
