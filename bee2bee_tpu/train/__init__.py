from .trainer import (  # noqa: F401
    TrainConfig,
    Trainer,
    TrainState,
    loss_fn,
    make_optimizer,
    make_train_state,
    make_train_step,
)
from .lora import (  # noqa: F401
    LoraConfig,
    LoraTrainer,
    init_lora,
    load_adapters,
    merge_lora,
    save_adapters,
)
