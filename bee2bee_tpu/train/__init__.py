from .trainer import (  # noqa: F401
    TrainConfig,
    Trainer,
    TrainState,
    loss_fn,
    make_optimizer,
    make_train_state,
    make_train_step,
)
