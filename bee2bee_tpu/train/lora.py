"""LoRA fine-tuning: low-rank adapters over the frozen base model.

The reference has no fine-tuning at all (its training protocol is a toy
per-layer MLP loop, reference node.py:99-182); this is a beyond-parity
capability, built the TPU way: adapters are stacked [L, ...] like the
base layers so the merged weights flow through the SAME `lax.scan`
transformer core (models/core.py) — one einsum over the layer dim merges
every layer's delta at once, and the whole merge lives INSIDE the jitted
train step, so XLA fuses it with the forward pass and the base weights'
TP sharding propagates to the merged result unchanged.

Freezing is by construction, not by optimizer masking: the merged weight
is `stop_gradient(W) + scaling * A @ B`, so `jax.grad` w.r.t. the
adapters is exact and the base never receives a gradient. Only the
adapters are optimizer state — Adam moments for a rank-8 distilgpt2
adapter set are ~100k floats, not 2x the model.

Usage:
    lcfg = LoraConfig(rank=8, targets=("wq", "wv"))
    trainer = LoraTrainer(model_cfg, base_params, lcfg, mesh=mesh)
    trainer.train_step(batch)                  # updates adapters only
    params = trainer.merged_params()           # serve/export (engine-ready)
    save_adapters(path, trainer.adapters, lcfg)  # ~MBs, not GBs
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..models import core
from ..models.config import ModelConfig
from ..models.partition import shard_params
from .trainer import (
    TrainConfig,
    TrainState,
    make_optimizer,
    make_step_from_loss,
    xent_loss_metrics,
)

# weights that can take an adapter: attention projections + MLP matmuls
ATTN_TARGETS = ("wq", "wk", "wv", "wo")
MLP_TARGETS = ("w_gate", "w_up", "w_down")


@dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    # alpha/rank scaling (the LoRA paper's convention: delta = alpha/r * AB)
    alpha: float = 16.0
    # which projections get adapters; q+v is the paper's sweet spot
    targets: tuple = ("wq", "wv")
    # init std of A (B is zero-init so training starts at the base model)
    init_std: float = 0.02

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank

    def __post_init__(self):
        bad = set(self.targets) - set(ATTN_TARGETS) - set(MLP_TARGETS)
        if bad:
            raise ValueError(
                f"unknown LoRA targets {sorted(bad)}; "
                f"known: {ATTN_TARGETS + MLP_TARGETS}"
            )
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")


def _group(target: str) -> str:
    return "attn" if target in ATTN_TARGETS else "mlp"


def validate_targets(cfg: ModelConfig, lcfg: LoraConfig) -> None:
    """Per-MODEL target check, run before any checkpoint load: the static
    LoraConfig check can't know that MoE models keep their MLP weights
    under layers['moe'] with an expert dim (unsupported for adapters), or
    that non-gated MLPs (gpt2's gelu) have no w_gate — failing here beats
    a KeyError after a multi-GB load."""
    mlp_t = [t for t in lcfg.targets if t in MLP_TARGETS]
    if cfg.is_moe and mlp_t:
        raise ValueError(
            f"LoRA MLP targets {mlp_t} unsupported on MoE model "
            f"{cfg.name!r} (expert weights are [L, E, ...]); use attention "
            f"targets {ATTN_TARGETS}"
        )
    if "w_gate" in lcfg.targets and cfg.activation not in ("silu", "geglu"):
        raise ValueError(
            f"target 'w_gate' does not exist on {cfg.name!r} "
            f"(activation={cfg.activation!r} is not gated)"
        )


def init_lora(
    cfg: ModelConfig, lcfg: LoraConfig, key, dtype=jnp.float32
) -> dict:
    """Adapters pytree: {target: {"a": [L, in, r], "b": [L, r, out]}}.
    Shapes come from the base layout (core.init_params docstring): wq is
    [L, D, H*hd], wk/wv [L, D, Hkv*hd], wo [L, H*hd, D], mlp [L, D, F]/
    [L, F, D]. B zero-init makes step 0 exactly the base model."""
    validate_targets(cfg, lcfg)
    io = adapter_target_io(cfg)
    adapters = {}
    for t in lcfg.targets:
        din, dout = io[t]
        key, ka = jax.random.split(key)
        adapters[t] = {
            "a": (jax.random.normal(ka, (cfg.n_layers, din, lcfg.rank), dtype)
                  * lcfg.init_std),
            "b": jnp.zeros((cfg.n_layers, lcfg.rank, dout), dtype),
        }
    return adapters


def merge_lora(
    base_params: dict, adapters: dict, lcfg: LoraConfig, trainable: bool = False
) -> dict:
    """Base params with each targeted weight replaced by W + s*(A@B),
    batched over the stacked layer dim. trainable=True stops gradients at
    the base so jax.grad flows only to the adapters (the train path);
    trainable=False produces engine-ready merged params (the serve path).
    Works on the host (numpy in) or inside jit (tracers in)."""
    params = dict(base_params)
    layers = dict(params["layers"])
    for t, ab in adapters.items():
        g = _group(t)
        grp = dict(layers[g])
        w = grp[t]
        # numpy base AND numpy adapters (the engine's host-side quantized-
        # load path) merge host-side — jnp there would device_put the full
        # dense weights, the exact allocation that path exists to avoid.
        # Tracer adapters (train step) force jnp even over a numpy base:
        # the base then enters the trace as a constant.
        xp = (
            np
            if isinstance(w, np.ndarray) and isinstance(ab["a"], np.ndarray)
            else jnp
        )
        if trainable:
            w = jax.lax.stop_gradient(w)
        delta = xp.einsum(
            "lir,lro->lio", xp.asarray(ab["a"], xp.float32),
            xp.asarray(ab["b"], xp.float32),
        ) * lcfg.scaling
        grp[t] = (w.astype(xp.float32) + delta).astype(grp[t].dtype)
        layers[g] = grp
    params["layers"] = layers
    return params


class LoraTrainer:
    """Adapter-only training over a frozen base. Reuses the SPMD step
    machinery (trainer.make_step_from_loss): with a mesh, the batch is
    DP/SP-sharded and the base weights keep their TP sharding — the
    replicated adapters broadcast into the merge einsum and XLA inserts
    the gradient psums."""

    def __init__(
        self,
        model_cfg: ModelConfig,
        base_params,
        lora_cfg: LoraConfig | None = None,
        train_cfg: TrainConfig | None = None,
        mesh=None,
        seed: int = 0,
    ):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.model_cfg = model_cfg
        self.lora_cfg = lora_cfg or LoraConfig()
        self.train_cfg = train_cfg or TrainConfig()
        self.mesh = mesh
        if mesh is not None:
            base_params = shard_params(base_params, mesh)
        self.base_params = base_params
        adapters = init_lora(
            model_cfg, self.lora_cfg, jax.random.key(seed),
            dtype=jnp.dtype(self.train_cfg.param_dtype),
        )
        if mesh is not None:  # adapters replicate: rank-r dims never shard
            rep = NamedSharding(mesh, P())
            adapters = jax.device_put(adapters, rep)
        opt = make_optimizer(self.train_cfg)
        self.state = TrainState(
            step=jnp.zeros((), jnp.int32), params=adapters,
            opt_state=opt.init(adapters),
        )

        def loss(adapters, batch):
            merged = merge_lora(
                self.base_params, adapters, self.lora_cfg, trainable=True
            )
            ids = batch["input_ids"]
            logits, _ = core.forward(
                merged, model_cfg, ids, None, jnp.int32(0),
                remat=self.train_cfg.remat,
            )
            return xent_loss_metrics(logits, ids, batch.get("loss_mask"))

        batch_sharding = (
            NamedSharding(mesh, P("data", "seq")) if mesh is not None else None
        )
        self._step = make_step_from_loss(loss, self.train_cfg, batch_sharding)

    @property
    def adapters(self):
        return self.state.params

    def train_step(self, batch: dict) -> dict:
        from .trainer import globalize_batch

        self.state, metrics = self._step(
            self.state, globalize_batch(batch, self.mesh)
        )
        return {k: float(v) for k, v in metrics.items()}

    def merged_params(self):
        """Engine-ready params: base + trained deltas, same pytree layout
        as core.init_params — drop them straight into InferenceEngine."""
        return merge_lora(self.base_params, self.adapters, self.lora_cfg)


class AdapterLoadError(ValueError):
    """Typed adapter load/validation failure: a corrupt file, a tampered
    tensor, or factors whose shapes don't match the declared LoraConfig.
    Raised HOST-side (load/validate time), so a bad adapter is a clean
    error to the one caller — never a shape crash inside a jitted step
    that would fail every in-flight request on the engine."""


# adapter .npz layout version. v2 adds the per-tensor sha256 manifest
# (__meta_sha256, pieces.py discipline); v1 files (no version key) load
# without verification for backward compatibility.
ADAPTER_FORMAT_VERSION = 2


def adapter_target_io(cfg: ModelConfig) -> dict:
    """{target: (din, dout)} against the base layout (core.init_params
    schema) — THE one copy of the per-target shape map, shared by
    init_lora, shape validation, and the serving pool's factor stacks
    (adapters/pool.py); two copies would silently desynchronize pool
    allocation from load-time validation."""
    D, H, Hkv, hd, F = (
        cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_ff
    )
    return {
        "wq": (D, H * hd), "wk": (D, Hkv * hd), "wv": (D, Hkv * hd),
        "wo": (H * hd, D),
        "w_gate": (D, F), "w_up": (D, F), "w_down": (F, D),
    }


def validate_adapter_shapes(cfg: ModelConfig, adapters, lcfg: LoraConfig,
                            max_rank: int | None = None) -> None:
    """Check every A/B factor against the base layout implied by `cfg` and
    the rank/targets `lcfg` declares. AdapterLoadError on any mismatch —
    the typed gate every consumer (engine merge, AdapterPool.load, mesh
    fetch) runs before factors go anywhere near a jit trace."""
    try:
        validate_targets(cfg, lcfg)
    except AdapterLoadError:
        raise
    except ValueError as e:
        # validate_targets raises bare ValueError (the training-time
        # surface); here a per-model target mismatch is still the typed
        # load error — a mesh fetch of a MoE-incompatible adapter must
        # not masquerade as an infrastructure fetch_failed incident
        raise AdapterLoadError(str(e)) from e
    io = adapter_target_io(cfg)
    if set(adapters) != set(lcfg.targets):
        raise AdapterLoadError(
            f"adapter targets {sorted(adapters)} != declared "
            f"{sorted(lcfg.targets)}"
        )
    if max_rank is not None and lcfg.rank > max_rank:
        raise AdapterLoadError(
            f"adapter rank {lcfg.rank} exceeds pool rank {max_rank}"
        )
    for t, ab in adapters.items():
        din, dout = io[t]
        a_shape = tuple(getattr(ab.get("a"), "shape", ()))
        b_shape = tuple(getattr(ab.get("b"), "shape", ()))
        if a_shape != (cfg.n_layers, din, lcfg.rank):
            raise AdapterLoadError(
                f"adapter {t!r}: A shape {a_shape} != "
                f"{(cfg.n_layers, din, lcfg.rank)} for {cfg.name!r}"
            )
        if b_shape != (cfg.n_layers, lcfg.rank, dout):
            raise AdapterLoadError(
                f"adapter {t!r}: B shape {b_shape} != "
                f"{(cfg.n_layers, lcfg.rank, dout)} for {cfg.name!r}"
            )


def save_adapters(path, adapters, lora_cfg: LoraConfig) -> None:
    """One .npz with the adapter arrays + a versioned manifest: the
    LoraConfig needed to merge (rank/alpha/targets — a mismatched merge
    would be silently wrong scaling) and a per-tensor sha256 map (the
    pieces.py discipline), so load_adapters turns a corrupt or tampered
    file into a typed AdapterLoadError instead of garbage weights."""
    import json

    from ..models.loader import _flatten
    from ..utils import sha256_hex

    flat = {k: np.asarray(v) for k, v in _flatten(jax.device_get(adapters)).items()}
    hashes = {
        k: sha256_hex(np.ascontiguousarray(v).tobytes()) for k, v in flat.items()
    }
    flat["__meta_version"] = np.int64(ADAPTER_FORMAT_VERSION)
    flat["__meta_rank"] = np.int64(lora_cfg.rank)
    flat["__meta_alpha"] = np.float64(lora_cfg.alpha)
    flat["__meta_targets"] = np.array(",".join(lora_cfg.targets))
    flat["__meta_sha256"] = np.array(json.dumps(hashes, separators=(",", ":")))
    np.savez(path, **flat)


def load_adapters(path, model_cfg: ModelConfig | None = None) -> tuple[dict, LoraConfig]:
    """Load + verify an adapter .npz. v2 files carry a per-tensor sha256
    manifest that is checked tensor-by-tensor; with ``model_cfg`` the
    factor shapes are additionally validated against the base layout.
    Any mismatch is a typed AdapterLoadError."""
    import json

    from ..models.loader import _unflatten
    from ..utils import sha256_hex

    try:
        with np.load(path, allow_pickle=False) as z:
            files = set(z.files)
            missing = {"__meta_rank", "__meta_alpha", "__meta_targets"} - files
            if missing:
                raise AdapterLoadError(
                    f"{path}: not an adapter file (missing {sorted(missing)})"
                )
            lcfg = LoraConfig(
                rank=int(z["__meta_rank"]),
                alpha=float(z["__meta_alpha"]),
                targets=tuple(str(z["__meta_targets"]).split(",")),
            )
            flat = {k: z[k] for k in z.files if not k.startswith("__meta_")}
            version = int(z["__meta_version"]) if "__meta_version" in files else 1
            if version >= 2:
                hashes = json.loads(str(z["__meta_sha256"]))
                if set(hashes) != set(flat):
                    raise AdapterLoadError(
                        f"{path}: manifest names {sorted(hashes)} != "
                        f"tensors {sorted(flat)}"
                    )
                for k, arr in flat.items():
                    got = sha256_hex(np.ascontiguousarray(arr).tobytes())
                    if got != hashes[k]:
                        raise AdapterLoadError(
                            f"{path}: tensor {k!r} hash mismatch "
                            f"({got[:12]} != {hashes[k][:12]})"
                        )
    except AdapterLoadError:
        raise
    except ValueError as e:  # LoraConfig validation (bad rank/targets)
        raise AdapterLoadError(f"{path}: {e}") from e
    except Exception as e:  # zipfile/np.load corruption
        raise AdapterLoadError(f"{path}: unreadable adapter file: {e}") from e
    adapters = _unflatten(flat)
    if model_cfg is not None:
        validate_adapter_shapes(model_cfg, adapters, lcfg)
    return adapters, lcfg
