"""Checkpoint/resume for training state (orbax-backed).

The reference has NO model-state checkpointing — only config JSON and
content-addressed weight pieces on disk (reference utils.py:37-40,
pieces.py:24-32); training activation caches live in process memory and
die with it (reference node.py:60,123-129). This module is the capability
*add* SURVEY §5 calls for: full TrainState (step/params/opt_state)
save/restore with orbax, sharding-aware restore onto a live Mesh so a
resumed run lands parameters directly at their mesh coordinates without a
host-memory detour.

Serving-side param checkpoints use the piece/manifest native format
(models/loader.py save_native) — the two interoperate via
``export_params``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models.partition import partition_specs
from .trainer import TrainConfig, TrainState, make_optimizer


class TrainCheckpointer:
    """Numbered step checkpoints under one directory, orbax-managed.

    Layout: ``<dir>/<step>/state`` (orbax PyTree) + ``<dir>/meta.json``
    (model/train configs, written once).
    """

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self.directory = Path(directory).absolute()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    # ------------------------------------------------------------------ save

    def save(
        self,
        state: TrainState,
        model_cfg: ModelConfig | None = None,
        train_cfg: TrainConfig | None = None,
        force: bool = False,
    ) -> int:
        step = int(state.step)
        if model_cfg is not None:
            meta = {
                "model": dict(model_cfg.__dict__),
                "train": dict(train_cfg.__dict__) if train_cfg else {},
            }
            (self.directory / "meta.json").write_text(
                json.dumps(meta, default=str, indent=1)
            )
        self._mgr.save(
            step, args=ocp.args.StandardSave(_to_saveable(state)), force=force
        )
        self._mgr.wait_until_finished()
        return step

    # --------------------------------------------------------------- restore

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def restore(
        self,
        model_cfg: ModelConfig,
        train_cfg: TrainConfig | None = None,
        mesh: Mesh | None = None,
        step: int | None = None,
    ) -> TrainState:
        """Restore a TrainState; with a mesh, leaves are produced directly
        at their partition_specs placements (no full-replica staging)."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        train_cfg = train_cfg or TrainConfig()
        template = _abstract_state(model_cfg, train_cfg, mesh)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(template)
        )
        # template already carries the optimizer-state tree structure, so the
        # restored pytree drops straight into TrainState
        return TrainState(
            step=restored["step"],
            params=restored["params"],
            opt_state=restored["opt_state"],
        )

    def close(self):
        self._mgr.close()

    # ------------------------------------------------------------- interop

    def export_params(self, state: TrainState, model_cfg: ModelConfig, path: str | Path):
        """Write serving-format weights (piece manifest, loader.save_native)
        from a training state — train → serve handoff."""
        from ..models.loader import save_native

        return save_native(state.params, model_cfg, path)


def load_meta(directory: str | Path) -> dict:
    p = Path(directory) / "meta.json"
    return json.loads(p.read_text()) if p.exists() else {}


# -------------------------------------------------------------------- helpers


def _to_saveable(state: TrainState) -> dict[str, Any]:
    # orbax StandardSave wants a pytree of arrays; dict container keeps the
    # on-disk layout stable across TrainState refactors
    return {
        "step": state.step,
        "params": state.params,
        "opt_state": state.opt_state,
    }


def _abstract_state(
    model_cfg: ModelConfig, train_cfg: TrainConfig, mesh: Mesh | None
) -> dict[str, Any]:
    """ShapeDtypeStructs (with shardings when a mesh is given) matching
    _to_saveable's layout, without materializing parameters."""
    from ..models import core

    dtype = jax.numpy.dtype(train_cfg.param_dtype)
    params_shape = jax.eval_shape(
        lambda: core.init_params(model_cfg, jax.random.key(0), dtype=dtype)
    )
    opt_shape = jax.eval_shape(
        lambda: make_optimizer(train_cfg).init(params_shape)
    )

    if mesh is not None:
        from .trainer import opt_partition_specs

        specs = partition_specs(params_shape)

        def with_sharding(leaf, spec):
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, spec)
            )

        # the ONE opt-spec builder (trainer.opt_partition_specs): keypath
        # matching + divisibility fallback + zero1 data-widening — a
        # --zero1 run's moments restore DATA-SHARDED; a replicated restore
        # template would materialize the full moments per replica (OOM at
        # exactly the scale zero1 exists for)
        zero1 = (
            getattr(train_cfg, "zero1", False) and mesh.shape.get("data", 1) > 1
        )
        opt_specs = opt_partition_specs(params_shape, opt_shape, mesh, zero1=zero1)
        params_shape = jax.tree.map(with_sharding, params_shape, specs)
        opt_shape = jax.tree.map(
            with_sharding, opt_shape, opt_specs,
        )

    return {
        "step": jax.ShapeDtypeStruct((), jax.numpy.int32)
        if mesh is None
        else jax.ShapeDtypeStruct(
            (), jax.numpy.int32, sharding=NamedSharding(mesh, P())
        ),
        "params": params_shape,
        "opt_state": opt_shape,
    }
