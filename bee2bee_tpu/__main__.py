"""CLI: `python -m bee2bee_tpu <command>` (reference __main__.py:30-123's
click group, with `serve-tpu` as the flagship alongside the reference's
backends and `register` for one-shot registry upserts)."""

from __future__ import annotations

import asyncio
import logging
import os

import click

from . import __version__
from .config import load_config, save_config


def _setup_logging():
    fmt = "%(asctime)s %(name)s %(levelname)s %(message)s"
    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"), format=fmt)
    # rotating file sink alongside stderr (the reference's loguru setup:
    # reference __main__.py:13-16). BEE2BEE_LOG_FILE overrides the path;
    # set it empty to disable. The default is per-PROCESS (pid suffix):
    # two processes rotating one shared file clobber each other's backups
    # — an explicit BEE2BEE_LOG_FILE opts into sharing deliberately.
    log_file = os.environ.get("BEE2BEE_LOG_FILE")
    if log_file is None:
        import contextlib
        import time as _time

        from .utils import bee2bee_home

        home = bee2bee_home()
        # reap per-pid logs of DEAD processes (>7 days) — a quiet but
        # live daemon's open log must never be unlinked out from under
        # its handler
        cutoff = _time.time() - 7 * 86400
        for old in home.glob("bee2bee-*.log*"):
            with contextlib.suppress(OSError, ValueError):
                pid = int(old.name.split("-", 1)[1].split(".", 1)[0])
                if pid == os.getpid():
                    continue
                try:
                    os.kill(pid, 0)  # raises if the pid is gone
                    continue  # still alive: keep its logs
                except ProcessLookupError:
                    pass
                except PermissionError:
                    continue  # alive under another uid
                if old.stat().st_mtime < cutoff:
                    old.unlink()
        log_file = str(home / f"bee2bee-{os.getpid()}.log")
    if log_file:
        from logging.handlers import RotatingFileHandler

        try:
            handler = RotatingFileHandler(
                log_file, maxBytes=5 * 1024 * 1024, backupCount=3
            )
            handler.setFormatter(logging.Formatter(fmt))
            logging.getLogger().addHandler(handler)
        except OSError:  # read-only fs etc. — stderr logging still works
            pass
    # orbax/absl emit per-save INFO floods; keep them at WARNING unless asked
    if os.environ.get("LOG_LEVEL", "INFO").upper() != "DEBUG":
        logging.getLogger("absl").setLevel(logging.WARNING)


def _apply_common_cfg(cfg, kw):
    """Fold _common_opts (and mesh shape) into the node config."""
    if kw.get("port") is not None:
        cfg.port = kw["port"]
    if kw.get("api_port") is not None:
        cfg.api_port = kw["api_port"]
    if kw.get("price") is not None:
        cfg.price_per_token = kw["price"]
    if kw.get("mesh_shape"):
        cfg.mesh_shape = kw["mesh_shape"]
    if kw.get("attention"):
        cfg.attention = kw["attention"]
    if kw.get("quantize"):
        cfg.quantize = kw["quantize"]
    if kw.get("kv_quant"):
        cfg.kv_quant = True
    if kw.get("paged"):
        cfg.paged = True
    if kw.get("spec_tokens") is not None:
        cfg.spec_tokens = kw["spec_tokens"]
    if kw.get("drafter") is not None:
        cfg.drafter = kw["drafter"]
    if kw.get("adapters"):
        cfg.adapters = kw["adapters"]
    if kw.get("max_adapters") is not None:
        cfg.max_adapters = kw["max_adapters"]
    return cfg


def _serve(backend: str, model: str, **kw):
    from .meshnet.runtime import run_p2p_node

    _setup_logging()
    cfg = _apply_common_cfg(load_config(), kw)
    try:
        asyncio.run(
            run_p2p_node(
                backend=backend,
                model=model,
                cfg=cfg,
                bootstrap=kw.get("bootstrap"),
                checkpoint_path=kw.get("checkpoint"),
                lora_path=kw.get("lora"),
                ollama_host=kw.get("ollama_host"),
                publish_weights=kw.get("publish_weights", False),
                from_mesh=kw.get("from_mesh", False),
                tunnel=kw.get("tunnel"),
            )
        )
    except KeyboardInterrupt:
        click.echo("shutting down")


def _microbatches_arg(ctx, param, value):
    """'auto' or an int >= 1 — validated at CLI parse, not minutes later
    inside the async serve body after the stages compiled."""
    if value == "auto":
        return value
    try:
        iv = int(value)
    except (TypeError, ValueError):
        raise click.BadParameter("must be 'auto' or a positive integer")
    if iv < 1:
        raise click.BadParameter("must be >= 1")
    return iv


def _common_opts(f):
    f = click.option("--port", type=int, default=None, help="WS mesh port")(f)
    f = click.option("--api-port", type=int, default=None, help="HTTP gateway port")(f)
    f = click.option("--bootstrap", default=None, help="bootstrap ws:// addr or join link")(f)
    f = click.option("--price", type=float, default=None, help="price per token")(f)
    f = click.option(
        "--tunnel",
        type=click.Choice(["auto", "bore", "ngrok", "cloudflared", "stub"]),
        default=None,
        help="expose this node through a public tunnel and announce its "
             "address (cloud/Colab onboarding — docs/CLOUD_NODE.md)",
    )(f)
    return f


@click.group()
@click.version_option(__version__)
def cli():
    """bee2bee-tpu: TPU-native decentralized inference mesh."""


@cli.command("serve-tpu")
@click.option("--model", default="distilgpt2",
              help="model name or config key; 'auto' derives the "
                   "architecture from --checkpoint's config.json (serves "
                   "checkpoints with no registry entry)")
@click.option("--checkpoint", default=None, help="local checkpoint dir (HF or native)")
@click.option("--lora", default=None, type=click.Path(exists=True),
              help="LoRA adapters .npz to merge over the base (bee2bee-tpu "
                   "train --lora-rank)")
@click.option("--mesh-shape", default=None, help='e.g. "data:1,model:8" or "seq:4,model:2"')
@click.option("--attention", type=click.Choice(["auto", "dense", "flash", "sp"]), default=None,
              help="auto (flash on TPU when supported) | dense | flash "
                   "(ragged paged pallas kernel; composes with --spec) | sp "
                   "(pool slot dim sharded over seq for long context)")
@click.option("--quantize", type=click.Choice(["none", "int8"]), default=None,
              help="weight-only quantization (int8 halves decode HBM traffic)")
@click.option("--kv-quant", "kv_quant", is_flag=True, default=False,
              help="int8 KV pool: pages stored int8 with per-page-per-head "
                   "scales, dequantized inside the attention kernels — ~2x "
                   "resident sessions at fixed HBM and half the migration "
                   "bytes (BEE2BEE_KV_QUANT; bf16 pool default)")
@click.option("--paged", is_flag=True, default=False,
              help="DEPRECATED no-op: the paged KV block pool is now the "
                   "only cache layout (per-step cache HBM traffic scales "
                   "with live tokens; prefix-cache hits share prompt "
                   "blocks copy-on-write, under every attention impl)")
@click.option("--spec", "spec_tokens", type=int, default=None,
              help="self-speculative decoding: draft up to N tokens per "
                   "step by n-gram lookup over the request's own "
                   "prompt+output and verify them in one batched forward "
                   "(greedy rows; BEE2BEE_SPEC; 0 = off)")
@click.option("--drafter", default=None,
              help="model-tier speculative drafter (requires --spec > 0): a "
                   "registry model name or checkpoint dir loaded resident "
                   "beside the target, or 'mesh' to stream drafts from a "
                   "BEE2BEE_DISAGG=draft peer. Rows where the n-gram tier "
                   "disables itself escalate to this tier instead of going "
                   "dark (BEE2BEE_DRAFTER; empty = n-gram only)")
@click.option("--adapters", default=None,
              help="batched multi-LoRA serving: comma-separated "
                   "name=path.npz adapters preloaded into the hot-swap "
                   "pool and published on the DHT — clients select one "
                   "via model='<base>:<name>' on /v1 (BEE2BEE_ADAPTERS; "
                   "composes with on-demand mesh paging)")
@click.option("--max-adapters", "max_adapters", type=int, default=None,
              help="adapter pool slots (BEE2BEE_MAX_ADAPTERS; --adapters "
                   "implies 8). Non-resident adapters page in from mesh "
                   "peers, LRU-evicting cold ones — no restart")
@click.option("--publish-weights", is_flag=True,
              help="announce this node's params as DHT pieces for joiners")
@click.option("--from-mesh", is_flag=True,
              help="fetch weights from mesh providers via the DHT "
                   "(zero local checkpoint)")
@_common_opts
def serve_tpu(model, checkpoint, lora, mesh_shape, attention, quantize,
              kv_quant, paged, spec_tokens, drafter, adapters, max_adapters,
              publish_weights, from_mesh, **kw):
    """Serve a model on TPU via the jit engine (the flagship entrypoint)."""
    _serve(
        "tpu", model, checkpoint=checkpoint, lora=lora, mesh_shape=mesh_shape,
        attention=attention, quantize=quantize, kv_quant=kv_quant, paged=paged,
        spec_tokens=spec_tokens, drafter=drafter, adapters=adapters,
        max_adapters=max_adapters,
        publish_weights=publish_weights, from_mesh=from_mesh, **kw
    )


@cli.command("serve-ollama")
@click.option("--model", required=True)
@click.option("--ollama-host", default=None, envvar="OLLAMA_HOST")
@_common_opts
def serve_ollama(model, ollama_host, **kw):
    """Proxy a local Ollama daemon into the mesh."""
    _serve("ollama", model, ollama_host=ollama_host, **kw)


@cli.command("serve-hf-remote")
@click.option("--model", required=True)
@_common_opts
def serve_hf_remote(model, **kw):
    """Proxy the HF serverless Inference API into the mesh."""
    _serve("hf_remote", model, **kw)


@cli.command("serve-stage")
@click.option("--model", required=True,
              help="model name or config key; 'auto' derives the "
                   "architecture from --checkpoint's config.json")
@click.option("--n-stages", type=int, default=None,
              help="preload this stage now (otherwise wait for part_load)")
@click.option("--stage", type=int, default=0, help="0-based stage index")
@click.option("--checkpoint", default=None, help="local checkpoint dir")
@click.option("--max-seq-len", type=int, default=2048)
@click.option("--quantize", type=click.Choice(["none", "int8"]), default="none",
              help="weight-only int8 of THIS stage's slice (halves its HBM)")
@_common_opts
def serve_stage(model, n_stages, stage, checkpoint, max_seq_len, quantize, **kw):
    """Host a pipeline-stage worker (layers [a, b) of a model).

    A coordinator peer drives generation across stage workers via the
    task protocol (part_load / part_forward — meshnet/pipeline.py); with
    --n-stages the stage loads immediately, otherwise the node waits for
    a coordinator's part_load."""
    from .meshnet.runtime import run_p2p_node

    _setup_logging()
    cfg = _apply_common_cfg(load_config(), kw)

    async def main():
        import functools

        from .engine.stage_runner import StageRunner

        preload = None
        if n_stages is not None:
            loop = asyncio.get_running_loop()
            preload = await loop.run_in_executor(
                None,
                functools.partial(
                    StageRunner,
                    model,
                    n_stages=n_stages,
                    stage=stage,
                    checkpoint_path=checkpoint,
                    max_seq_len=max_seq_len,
                    dtype=cfg.dtype,
                    quantize=quantize,
                ),
            )
        await run_p2p_node(
            backend=None,
            model=model,
            cfg=cfg,
            bootstrap=kw.get("bootstrap"),
            stage_runner=preload,
            tunnel=kw.get("tunnel"),
        )

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        click.echo("shutting down")


@cli.command("serve-pipeline")
@click.option("--model", required=True, help="model name or config key")
@click.option("--stage-peers", required=True,
              help="comma-separated ws:// addrs of serve-stage workers, "
                   "in stage order")
@click.option("--checkpoint", default=None,
              help="checkpoint dir readable by the WORKERS (part_load path)")
@click.option("--max-seq-len", type=int, default=2048)
@click.option("--max-batch", type=int, default=8,
              help="continuous-batching rows in the pipeline session")
@click.option("--microbatches", default="auto", callback=_microbatches_arg,
              help="'auto' (a compute-vs-hop depth from gossiped stage "
                   "timings on distinct hosts, legacy 2 without telemetry, "
                   "1 on a shared host) or an int >= 1; >1 runs that many "
                   "free-running microbatch groups whose chains interleave "
                   "across stages (costs proportionally more hops)")
@click.option("--quantize", type=click.Choice(["none", "int8"]), default="none",
              help="each stage int8-quantizes its slice at part_load")
@_common_opts
def serve_pipeline(model, stage_peers, checkpoint, max_seq_len,
                   max_batch, microbatches, quantize, **kw):
    """Coordinate a model SPLIT ACROSS stage workers and serve it as a
    normal mesh service (BASELINE config 4: layers [0,L/2) on one peer,
    [L/2,L) on another; activations hop as binary tensor frames).

    Start workers first (`serve-stage`), then this coordinator:
    part_load is pushed to every worker, and the chained generation is
    announced like any other model — gateway /chat, mesh gen_request,
    and streaming all work unchanged."""
    from .meshnet.pipeline import PipelineCoordinator
    from .meshnet.runtime import run_p2p_node
    from .services.pipeline import PipelineService

    _setup_logging()
    cfg = _apply_common_cfg(load_config(), kw)
    addrs = [a.strip() for a in stage_peers.split(",") if a.strip()]
    if not addrs:
        raise click.ClickException("no stage peers given")

    async def main():
        import asyncio as _asyncio

        async def setup(node):
            # dial the workers in stage order; peer ids come from hello
            peer_ids = []
            for addr in addrs:
                if not await node.connect_bootstrap(addr):
                    raise RuntimeError(f"cannot reach stage worker {addr}")
            for _ in range(100):
                peer_ids = [node.peer_for_addr(a) for a in addrs]
                if all(peer_ids):
                    break
                await _asyncio.sleep(0.1)
            if not all(peer_ids):
                raise RuntimeError(f"stage workers not identified: {addrs}")
            coordinator = PipelineCoordinator(
                node, model, stage_peers=peer_ids,
                max_seq_len=max_seq_len, dtype=cfg.dtype, quantize=quantize,
            )
            infos = await coordinator.load(checkpoint_path=checkpoint)
            for i, info in enumerate(infos):
                click.echo(f"stage {i} on {peer_ids[i]}: layers {info.get('layers')}")
            svc = PipelineService(
                coordinator, _asyncio.get_running_loop(), model,
                price_per_token=cfg.price_per_token,
                max_new_tokens=cfg.max_new_tokens,
                max_batch=max_batch, n_microbatches=microbatches,
                checkpoint_path=checkpoint,
            )
            await node.announce_service(svc)
            click.echo(f"pipeline model {model} serving; join link: {node.join_link()}")

        await run_p2p_node(
            backend=None, model=model, cfg=cfg,
            bootstrap=kw.get("bootstrap"), post_start=setup,
            tunnel=kw.get("tunnel"),
        )

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        click.echo("shutting down")


@cli.command("serve-fake")
@click.option("--model", default="fake-model")
@_common_opts
def serve_fake(model, **kw):
    """Serve a deterministic fake backend (testing/demo)."""
    _serve("fake", model, **kw)


@cli.command("serve-web")
@click.option("--seeds", default="", help="comma-separated ws:// node addrs")
@click.option("--port", type=int, default=4001, help="HTTP port for the web UI/API")
@click.option("--host", default="0.0.0.0")
def serve_web(seeds, port, host):
    """Run the browser-facing web gateway (the reference's Express/React
    tier, rebuilt on aiohttp + a static UI — bee2bee_tpu/web/)."""
    _setup_logging()

    async def main():
        from .registry import RegistryClient
        from .web import MeshBridge, start_web_gateway

        bridge = MeshBridge([s.strip() for s in seeds.split(",") if s.strip()])
        await bridge.start()
        registry = RegistryClient()
        runner = await start_web_gateway(
            bridge, host, port, registry=registry if registry.enabled else None
        )
        click.echo(f"web gateway: http://{host}:{port} (seeds: {bridge.seeds or '-'})")
        try:
            while True:
                await asyncio.sleep(3600)
        finally:
            await runner.cleanup()
            await bridge.stop()

    try:
        asyncio.run(main())
    except KeyboardInterrupt:
        click.echo("shutting down")


@cli.command()
@click.option("--bootstrap", default=None, help="set the default bootstrap url")
def register(bootstrap):
    """One-shot registry upsert + config update (reference __main__.py:78-123)."""
    _setup_logging()
    cfg = load_config()
    if bootstrap:
        cfg.bootstrap_url = bootstrap
        save_config(cfg)
        click.echo(f"bootstrap set to {bootstrap}")

    from .registry import RegistryClient

    client = RegistryClient()
    if not client.enabled:
        click.echo("registry disabled (no SUPABASE_URL/ANON_KEY or BEE2BEE_ENTRYPOINT)")
        return

    async def one_shot():
        from .meshnet.node import P2PNode

        node = P2PNode(host="127.0.0.1", port=0)
        await node.start()
        try:
            ok = await client.sync_node(node)
            click.echo(f"registry sync: {'ok' if ok else 'failed'}")
        finally:
            await node.stop()

    asyncio.run(one_shot())


@cli.command()
@click.option("--model", default="tiny-gpt2", help="model config name")
@click.option("--data", "data_path", required=True, type=click.Path(exists=True),
              help="text file (blank-line-separated documents)")
@click.option("--steps", default=100, help="training steps")
@click.option("--batch-size", default=8)
@click.option("--seq-len", default=128)
@click.option("--lr", default=3e-4)
@click.option("--ckpt-dir", default=None, help="checkpoint directory (resume if present)")
@click.option("--ckpt-every", default=50,
              help="steps between checkpoints (0 = only at the end)")
@click.option("--mesh-shape", default="", help='e.g. "data:2,model:4"')
@click.option("--coordinator", default=None, envvar="BEE2BEE_COORDINATOR",
              help="multi-host: host:port of process 0 (jax.distributed); "
                   "run the SAME command on every host")
@click.option("--num-hosts", type=int, default=1, envvar="BEE2BEE_NUM_HOSTS")
@click.option("--host-id", type=int, default=0, envvar="BEE2BEE_HOST_ID")
@click.option("--zero1", is_flag=True,
              help="shard optimizer state over the data axis (ZeRO-1): "
                   "saves ~2x params of HBM per replica")
@click.option("--checkpoint", "base_ckpt", default=None,
              help="base checkpoint dir (HF or native) to start from — "
                   "required context for --lora-rank finetuning")
@click.option("--lora-rank", type=int, default=0,
              help=">0: LoRA finetuning — train rank-r adapters over the "
                   "frozen base instead of full weights (train/lora.py)")
@click.option("--lora-alpha", type=float, default=16.0)
@click.option("--lora-targets", default="wq,wv",
              help="comma list from wq,wk,wv,wo,w_gate,w_up,w_down")
@click.option("--lora-out", default="lora_adapters.npz",
              help="where the trained adapters land (serve with "
                   "serve-tpu --lora PATH)")
def train(model, data_path, steps, batch_size, seq_len, lr, ckpt_dir, ckpt_every,
          mesh_shape, coordinator, num_hosts, host_id, zero1, base_ckpt,
          lora_rank, lora_alpha, lora_targets, lora_out):
    """Train a causal LM on a local text corpus (checkpoint/resume-able).

    The SPMD realization of the reference's per-layer WS training protocol
    (reference node.py:94-182). Multi-host: every host runs this same
    command with --coordinator host0:port --num-hosts N --host-id i; the
    mesh spans all hosts' chips, each host feeds its batch shard, and
    gradients ride XLA collectives over ICI/DCN (parallel/multihost.py)."""
    _setup_logging()
    if coordinator:
        # must run BEFORE anything touches the jax backend
        from .parallel.multihost import init_multihost

        init_multihost(coordinator, num_processes=num_hosts, process_id=host_id)
    from .datasets import PreprocessConfig, from_text_file
    from .engine.tokenizer import ByteTokenizer
    from .models.config import get_config
    from .train.trainer import TrainConfig, Trainer

    cfg = get_config(model)
    tcfg = TrainConfig(learning_rate=lr, total_steps=steps, zero1=zero1)
    mesh = None
    if mesh_shape:
        from .config import parse_mesh_shape
        from .parallel import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec.from_dict(parse_mesh_shape(mesh_shape)))
    elif coordinator:
        # multi-host without an explicit shape: mesh=None would make every
        # host run an identical independent single-device job (and race on
        # the checkpoint dir) — default to data-parallel over ALL hosts'
        # devices instead
        import jax

        from .parallel import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(data=len(jax.devices())))
        click.echo(f"multi-host: defaulting mesh to data:{len(jax.devices())}")

    data = from_text_file(
        data_path, ByteTokenizer(cfg.vocab_size),
        PreprocessConfig(seq_len=seq_len, batch_size=batch_size, shuffle_seed=0),
    )
    if data.n_batches == 0:
        raise click.ClickException("corpus too small for one batch")

    lcfg = None
    if lora_rank > 0:
        # config errors (bad targets for THIS model) must surface before
        # the multi-GB base checkpoint load below
        from .train.lora import LoraConfig, validate_targets

        try:
            lcfg = LoraConfig(rank=lora_rank, alpha=lora_alpha,
                              targets=tuple(lora_targets.split(",")))
            validate_targets(cfg, lcfg)
        except ValueError as e:
            raise click.ClickException(str(e))
        if ckpt_dir or zero1:
            # fail loudly AND before the multi-GB base load below:
            # discovering after a 5000-step run (or a minutes-long load)
            # that --ckpt-dir did nothing is worse than re-running
            raise click.ClickException(
                "--ckpt-dir/--zero1 do not apply to LoRA runs; adapters "
                "are checkpointed to --lora-out every --ckpt-every steps"
            )

    base_params = None
    if base_ckpt:
        import jax.numpy as jnp

        from .models.loader import load_checkpoint

        # the trainer's master-param dtype, NOT the serving default (bf16
        # masters round away ~1e-4-relative Adam updates — loss plateaus)
        base_params = load_checkpoint(
            base_ckpt, cfg, dtype=jnp.dtype(tcfg.param_dtype)
        )

    if lora_rank > 0:
        from .train.lora import LoraTrainer, save_adapters

        if base_params is None:
            from .models import core as _core

            import jax as _jax

            click.echo("warning: --lora-rank without --checkpoint trains "
                       "adapters over a RANDOM base (test runs only)")
            base_params = _core.init_params(cfg, _jax.random.key(0))
        ltr = LoraTrainer(cfg, base_params, lcfg, tcfg, mesh=mesh)
        it = data.repeat()
        while int(ltr.state.step) < steps:
            metrics = ltr.train_step(next(it))
            s = int(ltr.state.step)
            if s % 10 == 0 or s == steps:
                click.echo(f"step {s:5d} loss {metrics['loss']:.4f} "
                           f"acc {metrics['accuracy']:.3f}")
            if ckpt_every > 0 and s % ckpt_every == 0 and s < steps:
                save_adapters(lora_out, ltr.adapters, lcfg)
        save_adapters(lora_out, ltr.adapters, lcfg)
        click.echo(f"adapters -> {lora_out} (serve: bee2bee-tpu serve-tpu "
                   f"--model {model} --lora {lora_out})")
        return

    ckpt = None
    trainer = Trainer(cfg, tcfg, mesh=mesh, params=base_params)
    if ckpt_dir:
        from .train.checkpoint import TrainCheckpointer

        ckpt = TrainCheckpointer(ckpt_dir)
        if ckpt.latest_step() is not None:
            trainer.state = ckpt.restore(cfg, tcfg, mesh=mesh)
            click.echo(f"resumed from step {trainer.step}")

    it = data.repeat()
    while trainer.step < steps:
        metrics = trainer.train_step(next(it))
        if trainer.step % 10 == 0 or trainer.step == steps:
            click.echo(
                f"step {trainer.step:5d} loss {metrics['loss']:.4f} "
                f"acc {metrics['accuracy']:.3f}"
            )
        if ckpt and (
            (ckpt_every > 0 and trainer.step % ckpt_every == 0)
            or trainer.step == steps
        ):
            ckpt.save(trainer.state, cfg, tcfg)
    if ckpt:
        ckpt.close()


@cli.command("export")
@click.option("--model", required=True, help="model name or config key")
@click.option("--checkpoint", default=None,
              help="source checkpoint dir (HF or native); random init if omitted")
@click.option("--out", "out_dir", required=True, help="output directory")
@click.option("--format", "fmt", type=click.Choice(["hf", "native"]), default="hf",
              help="hf: safetensors + config.json any transformers stack "
                   "loads; native: content-addressed pieces + manifest")
@click.option("--dtype", default="float32",
              help="export dtype (float32/float16/bfloat16)")
def export_cmd(model, checkpoint, out_dir, fmt, dtype):
    """Export a model checkpoint to an interchange format.

    The TPU-native analogue of the reference's TorchScript/ONNX export
    (reference hf.py:139-158): torch graph formats make no sense for a
    jax stack, so the interchange surface is HF-layout safetensors
    (loadable by torch/transformers) or the native piece format used for
    mesh weight distribution."""
    _setup_logging()
    import jax
    import jax.numpy as jnp

    from .models import core, get_config
    from .models.export import export_hf
    from .models.loader import load_checkpoint, save_native

    cfg = get_config(model)
    if checkpoint:
        params = load_checkpoint(checkpoint, cfg, dtype=jnp.float32)
    else:
        click.echo("no --checkpoint: exporting random-init params")
        params = core.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    if fmt == "hf":
        out = export_hf(params, cfg, out_dir, dtype=dtype)
    else:
        if dtype != "float32":  # honor --dtype for native pieces too
            params = jax.tree.map(lambda a: a.astype(jnp.dtype(dtype)), params)
        save_native(params, cfg, out_dir)
        out = out_dir
    click.echo(f"exported {cfg.name} ({fmt}) -> {out}")


@cli.command("nat-status")
@click.option("--port", default=4003, help="port to attempt forwarding for")
@click.option("--forward/--no-forward", default=False,
              help="actually create a mapping (touches the router)")
def nat_status(port, forward):
    """NAT diagnostics: gateway, public IP, NAT type, optional forward
    (reference nat.py:493-561's status table)."""
    _setup_logging()
    from . import nat
    from .stun import STUNClient

    click.echo(f"lan ip:     {nat.get_lan_ip()}")
    click.echo(f"gateway:    {nat.get_gateway_ip()}")
    click.echo(f"public ip:  {nat.get_public_ip()}")
    click.echo(f"nat type:   {STUNClient().detect_nat_type()}")
    if forward:
        mapping = nat.auto_forward_port(port)
        click.echo(
            f"forward:    ok={mapping.ok} method={mapping.method} "
            f"external={mapping.public_ip}:{mapping.external_port} {mapping.detail}"
        )


@cli.command()
def info():
    """Show devices, mesh defaults, and config."""
    import jax

    cfg = load_config()
    click.echo(f"version: {__version__}")
    click.echo(f"devices: {jax.devices()}")
    click.echo(f"config: {cfg.to_dict()}")


if __name__ == "__main__":
    cli()
