"""ctypes bindings for the C++ piece codec (native/src/*.cpp).

The reference is pure Python (SURVEY executive summary: "zero
C++/Rust/CUDA/native components"); this framework's runtime keeps a
native data plane where it pays: content-hashing model-weight pieces.
`hashlib` releases the GIL per call but Python still iterates pieces
serially — the C++ codec hashes all pieces of a checkpoint across cores
in one call.

Degrades gracefully: if the shared object is missing we try one quiet
`make` (g++ is in the image); if that fails, every function falls back
to hashlib so the framework never hard-requires the native build.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
from pathlib import Path

logger = logging.getLogger("bee2bee_tpu.native")

_SO_PATH = Path(__file__).parent / "_native" / "libbee2bee.so"
_NATIVE_DIR = Path(__file__).parent.parent / "native"
_lib = None
_load_attempted = False


def _try_build() -> bool:
    if not (_NATIVE_DIR / "Makefile").exists():
        return False
    try:
        subprocess.run(
            ["make", "-C", str(_NATIVE_DIR)],
            capture_output=True,
            timeout=120,
            check=True,
        )
        return _SO_PATH.exists()
    except (subprocess.SubprocessError, OSError) as e:
        logger.debug("native build failed: %s", e)
        return False


def _load():
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("BEE2BEE_DISABLE_NATIVE", "").lower() in ("1", "true", "yes"):
        return None
    if not _SO_PATH.exists() and not _try_build():
        logger.info("native codec unavailable; using hashlib fallback")
        return None
    try:
        lib = ctypes.CDLL(str(_SO_PATH))
        lib.b2b_version.restype = ctypes.c_char_p
        lib.b2b_sha256.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_void_p
        ]
        lib.b2b_hash_many.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.c_void_p,
            ctypes.c_int,
        ]
        lib.b2b_hash_chunks.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_void_p, ctypes.c_int,
        ]
        lib.b2b_hash_chunks.restype = ctypes.c_uint64
        lib.b2b_verify_many.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64,
            ctypes.c_void_p,
            ctypes.c_int,
        ]
        lib.b2b_verify_many.restype = ctypes.c_int64
        lib.b2b_sha256_accelerated.restype = ctypes.c_int
        _lib = lib
    except (OSError, AttributeError) as e:
        # AttributeError = a stale prebuilt .so missing a newer symbol
        # (the file is gitignored, so it survives source updates); degrade
        # to hashlib rather than crashing every entry point
        logger.warning(
            "failed to load native codec (%s); falling back to hashlib — "
            "run `make -C native clean all` to rebuild", e
        )
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def version() -> str | None:
    lib = _load()
    return lib.b2b_version().decode() if lib else None


def accelerated() -> bool:
    """True when the codec resolved libcrypto's SHA256 (SHA-NI/AVX2) —
    the fast path that makes multi-GB checkpoint hashing ~10x quicker
    than the portable fallback."""
    lib = _load()
    return bool(lib and lib.b2b_sha256_accelerated())


def _ptr_arrays(blobs: list[bytes]):
    n = len(blobs)
    datas = (ctypes.c_char_p * n)(*blobs)
    lens = (ctypes.c_uint64 * n)(*[len(b) for b in blobs])
    return datas, lens


def sha256_hex(data: bytes) -> str:
    lib = _load()
    if lib is None:
        return hashlib.sha256(data).hexdigest()
    out = (ctypes.c_uint8 * 32)()
    lib.b2b_sha256(data, len(data), out)
    return bytes(out).hex()


def hash_many(blobs: list[bytes], n_threads: int = 0) -> list[str]:
    """Parallel sha256 of many buffers; [] -> []."""
    if not blobs:
        return []
    lib = _load()
    if lib is None:
        return [hashlib.sha256(b).hexdigest() for b in blobs]
    datas, lens = _ptr_arrays(blobs)
    out = (ctypes.c_uint8 * (32 * len(blobs)))()
    lib.b2b_hash_many(datas, lens, len(blobs), out, n_threads)
    raw = bytes(out)
    return [raw[i * 32 : (i + 1) * 32].hex() for i in range(len(blobs))]


def hash_chunks(data: bytes, piece_size: int, n_threads: int = 0) -> list[str]:
    """Hash consecutive piece_size chunks of one buffer without splitting
    it into Python objects first."""
    if not data:
        return []
    lib = _load()
    if lib is None:
        return [
            hashlib.sha256(data[i : i + piece_size]).hexdigest()
            for i in range(0, len(data), piece_size)
        ]
    n = -(-len(data) // piece_size)
    out = (ctypes.c_uint8 * (32 * n))()
    got = lib.b2b_hash_chunks(data, len(data), piece_size, out, n_threads)
    raw = bytes(out)
    return [raw[i * 32 : (i + 1) * 32].hex() for i in range(got)]


def verify_many(blobs: list[bytes], hex_digests: list[str], n_threads: int = 0) -> int:
    """Return -1 if every blob matches its digest, else the lowest
    mismatching index."""
    if len(blobs) != len(hex_digests):
        raise ValueError(f"count mismatch: {len(blobs)} blobs, {len(hex_digests)} digests")
    if not blobs:
        return -1
    lib = _load()
    if lib is None:
        for i, (b, h) in enumerate(zip(blobs, hex_digests)):
            if hashlib.sha256(b).hexdigest() != h:
                return i
        return -1
    datas, lens = _ptr_arrays(blobs)
    expected = bytes.fromhex("".join(hex_digests))
    return lib.b2b_verify_many(datas, lens, len(blobs), expected, n_threads)
