"""The node-facing observatory: collectors + sampling loop + trend digest.

One `Observatory` per node ties the pieces together: a curated set of
**collectors** (callables returning the current value of one series, or
None for "subsystem not running") feeds a `TsRing` on a fixed cadence
driven by the node's injected clock, a `TrendWatchdog` examines every
sample, and the resulting trend digest rides the TELEMETRY gossip so
the router's degrading penalty and the fleet controller's pool forecast
can act on *slopes*, not just instants.

Collectors are injectable (`set_collector`) — the simnet regression
test scripts a deterministic acceptance collapse as a pure function of
virtual time; production nodes use the registry-backed defaults below.
"""

from __future__ import annotations

import statistics
from typing import Callable, Mapping

from ..clock import Clock, resolve_clock
from ..metrics import Counter, Gauge, Histogram, MetricsRegistry, get_registry
from .tsring import OBS_CADENCE_S, OBS_CAPACITY, SERIES_NAMES, TsRing
from .watchdog import TREND_DIGEST_VERSION, TrendPolicy, TrendWatchdog

Collector = Callable[[], "float | None"]

_REG = get_registry()
_C_SAMPLES = _REG.counter(
    "obs.samples", "observatory ring samples taken"
)
_C_ANOMALIES = _REG.counter(
    "obs.anomalies", "trend-watchdog anomalies fired (by series)"
)
_G_RING_POINTS = _REG.gauge(
    "obs.ring_points", "samples currently retained in the observatory ring"
)


def _gauge_mean(reg: MetricsRegistry, name: str) -> float | None:
    m = reg.get(name)
    if not isinstance(m, Gauge):
        return None
    series = m.series()
    if not series:
        return None
    return statistics.fmean(v for _, v in series)


def _gauge_max(reg: MetricsRegistry, name: str) -> float | None:
    m = reg.get(name)
    if not isinstance(m, Gauge):
        return None
    series = m.series()
    if not series:
        return None
    return max(v for _, v in series)


def _hist_p95(reg: MetricsRegistry, name: str) -> float | None:
    m = reg.get(name)
    if not isinstance(m, Histogram):
        return None
    count, _ = m.totals()
    if count == 0:
        return None
    return m.percentile(0.95)


def _pool_free_frac(reg: MetricsRegistry) -> float | None:
    total = reg.get("engine.paged_blocks_total")
    free = reg.get("engine.paged_blocks_free")
    if not isinstance(total, Gauge) or not isinstance(free, Gauge):
        return None
    if not total.series():
        return None
    t = total.value()
    if t <= 0:
        return None
    return min(max(free.value() / t, 0.0), 1.0)


class _CounterRate:
    """Per-interval rate of a cumulative counter (None until the second
    sample, and across a registry reset's backwards jump)."""

    def __init__(self, reg: MetricsRegistry, name: str, clock: Clock):
        self._reg, self._name, self._clock = reg, name, clock
        self._last: tuple[float, float] | None = None

    def __call__(self) -> float | None:
        m = self._reg.get(self._name)
        if not isinstance(m, Counter):
            return None
        now, cur = self._clock.time(), m.total()
        last, self._last = self._last, (now, cur)
        if last is None:
            return None
        dt, dv = now - last[0], cur - last[1]
        if dt <= 0 or dv < 0:
            return None
        return dv / dt


class _AcceptanceRate:
    """Per-interval spec acceptance: accepted-delta / drafted-delta —
    the *current* acceptance, unlike the digest's cumulative ratio whose
    inertia hides a mid-run collapse (exactly what the watchdog hunts)."""

    def __init__(self, reg: MetricsRegistry):
        self._reg = reg
        self._last: tuple[float, float] | None = None

    def __call__(self) -> float | None:
        acc = self._reg.get("engine.spec_accepted")
        dra = self._reg.get("engine.spec_drafted")
        if not isinstance(acc, Counter) or not isinstance(dra, Counter):
            return None
        cur = (acc.total(), dra.total())
        last, self._last = self._last, cur
        if last is None:
            return None
        d_acc, d_dra = cur[0] - last[0], cur[1] - last[1]
        if d_dra <= 0 or d_acc < 0:
            return None
        return min(d_acc / d_dra, 1.0)


def default_collectors(
    node=None,
    registry: MetricsRegistry | None = None,
    clock: Clock | None = None,
) -> dict[str, Collector]:
    """Registry-backed collectors for the curated series set. Node-local
    signals (SLO burn, peer RTT) degrade to None without a node."""
    reg = registry or get_registry()
    ck = resolve_clock(clock)

    def slo_burn() -> float | None:
        if node is not None:
            try:
                return float(node.slo.max_fast_burn())
            except Exception:  # noqa: BLE001 — telemetry never throws
                return None
        return _gauge_max(reg, "slo.burn_rate")

    def peer_rtt() -> float | None:
        if node is None:
            return None
        rtts = [
            info.get("rtt_ms")
            for info in list(node.peers.values())
            if info.get("rtt_ms") is not None
        ]
        return statistics.fmean(rtts) if rtts else None

    return {
        "decode_tok_s": _CounterRate(reg, "engine.tokens_generated", ck),
        "goodput_tok_s": lambda: _gauge_mean(reg, "engine.goodput_tokens_per_s"),
        "mfu": lambda: _gauge_mean(reg, "engine.mfu"),
        "spec_acceptance": _AcceptanceRate(reg),
        "queue_wait_p95_ms": lambda: _hist_p95(reg, "engine.queue_wait_ms"),
        "pool_free_frac": lambda: _pool_free_frac(reg),
        "pipeline_bubble": lambda: _gauge_mean(reg, "pipeline.bubble_fraction"),
        "slo_burn_fast": slo_burn,
        "peer_rtt_ms": peer_rtt,
    }


class Observatory:
    """TsRing + watchdog + collectors behind one sampling loop."""

    def __init__(
        self,
        node=None,
        clock: Clock | None = None,
        cadence_s: float = OBS_CADENCE_S,
        capacity: int = OBS_CAPACITY,
        collectors: Mapping[str, Collector] | None = None,
        policies: Mapping[str, TrendPolicy] | None = None,
        recorder=None,
        registry: MetricsRegistry | None = None,
    ):
        self.node = node
        self.clock = resolve_clock(
            clock if clock is not None else getattr(node, "clock", None)
        )
        self.cadence_s = float(cadence_s)
        self.ring = TsRing(
            SERIES_NAMES, cadence_s=self.cadence_s, capacity=capacity,
            clock=self.clock,
        )
        self.watchdog = TrendWatchdog(
            self.ring,
            policies=policies,
            recorder=recorder,
            node_id=getattr(node, "peer_id", None),
            clock=self.clock,
        )
        self.collectors: dict[str, Collector] = dict(
            collectors
            if collectors is not None
            else default_collectors(node, registry=registry, clock=self.clock)
        )

    def set_collector(self, name: str, fn: Collector) -> None:
        self.collectors[name] = fn

    # ----------------------------------------------------------- sampling

    def sample_once(self) -> dict[str, float | None]:
        """Collect every series (per-collector never-throw), append one
        ring snapshot, run the watchdog. Returns the collected values."""
        values: dict[str, float | None] = {}
        for name, fn in self.collectors.items():
            try:
                values[name] = fn()
            except Exception:  # noqa: BLE001 — telemetry never throws
                values[name] = None
        self.ring.append(values)
        _C_SAMPLES.inc()
        _G_RING_POINTS.set(float(len(self.ring)))
        for anom in self.watchdog.observe():
            _C_ANOMALIES.inc(series=anom["series"])
        return values

    async def run(self, stopped: Callable[[], bool]) -> None:
        """The sampling loop (spawned by P2PNode.start): one snapshot per
        cadence on the injected clock until ``stopped()``. Never-throw —
        a broken collector must not kill the node's task group."""
        while not stopped():
            await self.clock.sleep(self.cadence_s)
            if stopped():
                return
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — telemetry never throws
                pass

    # ------------------------------------------------------------ queries

    def history(
        self,
        names=None,
        window_s: float | None = None,
        raw: bool = False,
    ) -> dict:
        """Per-series curves for /metrics/history: delta-encoded by
        default, ``raw=True`` for plain ``[[ts, v], ...]`` points."""
        if raw:
            return {
                name: [[t, v] for t, v in pts]
                for name, pts in self.ring.window(names, window_s).items()
            }
        return self.ring.encode(names, window_s)

    def trend_digest(self) -> dict | None:
        """The compact trend block riding the TELEMETRY digest, or None
        before the watchdog has two samples of anything (the
        absent-subsystem contract: no history, no key)."""
        series = self.watchdog.snapshot()
        if not series:
            return None
        return {
            "v": TREND_DIGEST_VERSION,
            "cadence_s": self.cadence_s,
            "series": series,
        }
