"""Fleet observatory (ISSUE 20): retained time-series, trend watchdog,
and the trend digest the router/controller act on.

- `tsring`: fixed-cadence bounded ring + delta encoding (clock seam)
- `watchdog`: EWMA + slope/level-shift change-point detection, typed
  ``trend:<series>`` FlightRecorder incidents
- `observatory`: collectors + sampling loop + trend digest
"""

from .observatory import Observatory, default_collectors
from .tsring import (
    OBS_CADENCE_S,
    OBS_CAPACITY,
    SERIES,
    SERIES_BY_NAME,
    SERIES_NAMES,
    SeriesSpec,
    TsRing,
    delta_decode,
    delta_encode,
)
from .watchdog import TREND_DIGEST_VERSION, TrendPolicy, TrendWatchdog

__all__ = [
    "OBS_CADENCE_S",
    "OBS_CAPACITY",
    "SERIES",
    "SERIES_BY_NAME",
    "SERIES_NAMES",
    "SeriesSpec",
    "TsRing",
    "delta_decode",
    "delta_encode",
    "TREND_DIGEST_VERSION",
    "TrendPolicy",
    "TrendWatchdog",
    "Observatory",
    "default_collectors",
]
