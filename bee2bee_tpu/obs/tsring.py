"""Retained time-series: the fleet observatory's storage primitive.

Every observability surface before this PR was point-in-time — /metrics
is cumulative, the health digest is the latest snapshot. The TsRing is
the missing primitive: a fixed-cadence, bounded ring of samples over a
curated series set, so degradation is a queryable *curve* (and a
detectable slope — obs/watchdog.py) rather than a scrape-time instant.

Contracts:

- **Clock seam**: the ring stamps samples from an injected `Clock`
  (clock.py), never wall time, so a simnet run in virtual time replays
  the retained history bit-identically across same-seed runs.
- **Bounded**: `capacity` samples, oldest evicted. At the default 5 s
  cadence, 720 samples retain one hour.
- **Delta encoding**: the wire/query form (`encode`) quantizes values to
  per-series fixed-point integers and ships first-value + deltas, so a
  1 h window stays a few KB of JSON. Quantization is integer-exact:
  `delta_decode(delta_encode(pts))` reproduces `round(v, precision)`
  with no float accumulation drift.
- **Absent-subsystem contract**: a collector returning None (no engine,
  no peers) stores a gap; gaps are skipped in points/encodes, matching
  the digest's "absent means not running, not zero" rule.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Iterable, Mapping

from ..clock import Clock, resolve_clock

# production sampling defaults: one sample per OBS_CADENCE_S, one hour
# retained. Overridable per-node via BEE2BEE_OBS_CADENCE_S (node.py).
OBS_CADENCE_S = 5.0
OBS_CAPACITY = 720


@dataclass(frozen=True)
class SeriesSpec:
    """One curated series: identity plus the rules every consumer needs.

    - ``agg``: how /mesh/history merges peers into a fleet curve —
      throughput series sum, level/fraction series average.
    - ``direction``: which way is degradation ("up_bad": rising queue
      wait is bad; "down_bad": falling acceptance is bad). The watchdog
      only alarms in the bad direction.
    - ``precision``: decimal places kept by the delta encoding.
    - ``scale_floor``: denominator floor when normalizing slopes to
      "fraction of the level per minute" — keeps a near-zero baseline
      from reading as an infinite relative slope.
    """

    name: str
    unit: str
    agg: str  # "sum" | "mean"
    direction: str  # "up_bad" | "down_bad"
    precision: int
    scale_floor: float


# The curated series set (ISSUE 20). Names are the wire vocabulary:
# /metrics/history keys, trend-digest keys, and `trend:<series>`
# incident kinds all use them verbatim, so they are append-only.
SERIES: tuple[SeriesSpec, ...] = (
    SeriesSpec("decode_tok_s", "tok/s", "sum", "down_bad", 2, 1.0),
    SeriesSpec("goodput_tok_s", "tok/s", "sum", "down_bad", 2, 1.0),
    SeriesSpec("mfu", "fraction", "mean", "down_bad", 4, 0.01),
    SeriesSpec("spec_acceptance", "fraction", "mean", "down_bad", 4, 0.05),
    SeriesSpec("queue_wait_p95_ms", "ms", "mean", "up_bad", 2, 1.0),
    SeriesSpec("pool_free_frac", "fraction", "mean", "down_bad", 4, 0.05),
    SeriesSpec("pipeline_bubble", "fraction", "mean", "up_bad", 4, 0.05),
    SeriesSpec("slo_burn_fast", "ratio", "mean", "up_bad", 3, 0.1),
    SeriesSpec("peer_rtt_ms", "ms", "mean", "up_bad", 2, 1.0),
)
SERIES_BY_NAME: dict[str, SeriesSpec] = {s.name: s for s in SERIES}
SERIES_NAMES: tuple[str, ...] = tuple(s.name for s in SERIES)

# shared slope-normalization floor for series NOT in the catalog (unit
# digests over ad-hoc series); catalog series carry their own.
DEFAULT_SCALE_FLOOR = 1.0


def _precision(name: str) -> int:
    spec = SERIES_BY_NAME.get(name)
    return spec.precision if spec is not None else 4


def delta_encode(points: list[tuple[float, float]], precision: int = 4) -> dict:
    """Quantize ``[(ts, value), ...]`` to fixed-point and delta-encode.

    Timestamps quantize to milliseconds, values to ``precision`` decimal
    places; both ship as first-value + integer deltas so a steady series
    costs ~2 digits per sample instead of a float per sample."""
    if not points:
        return {"n": 0, "p": precision}
    vq = 10 ** precision
    ts_q = [int(round(t * 1000.0)) for t, _ in points]
    vs_q = [int(round(v * vq)) for _, v in points]
    return {
        "n": len(points),
        "p": precision,
        "t0": ts_q[0],
        "td": [b - a for a, b in zip(ts_q, ts_q[1:])],
        "v0": vs_q[0],
        "vd": [b - a for a, b in zip(vs_q, vs_q[1:])],
    }


def delta_decode(enc: Mapping) -> list[tuple[float, float]]:
    """Inverse of `delta_encode`: integer-exact up to the quantization."""
    n = int(enc.get("n") or 0)
    if n == 0:
        return []
    vq = 10 ** int(enc.get("p") or 0)
    t = int(enc["t0"])
    v = int(enc["v0"])
    out = [(t / 1000.0, v / vq)]
    for dt, dv in zip(enc.get("td") or [], enc.get("vd") or []):
        t += int(dt)
        v += int(dv)
        out.append((t / 1000.0, v / vq))
    return out


class TsRing:
    """Fixed-cadence bounded ring of snapshots over a fixed series set.

    Columnar: one shared timestamp ring plus one value ring per series
    (None marks a gap). Thread-safe — sampled on the node's loop but
    read from API handlers and the bench harness's timing threads."""

    def __init__(
        self,
        series: Iterable[str] = SERIES_NAMES,
        cadence_s: float = OBS_CADENCE_S,
        capacity: int = OBS_CAPACITY,
        clock: Clock | None = None,
    ):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.cadence_s = float(cadence_s)
        self.capacity = int(capacity)
        self._clock = resolve_clock(clock)
        self._lock = threading.Lock()
        self._ts: deque[float] = deque(maxlen=self.capacity)
        self._cols: dict[str, deque] = {
            str(name): deque(maxlen=self.capacity) for name in series
        }
        if not self._cols:
            raise ValueError("TsRing needs at least one series")

    @property
    def series(self) -> tuple[str, ...]:
        return tuple(self._cols)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ts)

    def append(self, values: Mapping[str, float | None], ts: float | None = None) -> float:
        """Record one snapshot (missing/unknown series store a gap).
        Returns the stamp used — the injected clock's now by default."""
        stamp = self._clock.time() if ts is None else float(ts)
        with self._lock:
            self._ts.append(stamp)
            for name, col in self._cols.items():
                v = values.get(name)
                col.append(float(v) if v is not None else None)
        return stamp

    def points(
        self, name: str, window_s: float | None = None
    ) -> list[tuple[float, float]]:
        """``[(ts, value), ...]`` for one series, gaps skipped, optionally
        restricted to the trailing ``window_s`` of retained time."""
        col = self._cols.get(name)
        if col is None:
            return []
        with self._lock:
            ts = list(self._ts)
            vs = list(col)
        if window_s is not None and ts:
            cutoff = ts[-1] - float(window_s)
            out = [(t, v) for t, v in zip(ts, vs) if v is not None and t >= cutoff]
        else:
            out = [(t, v) for t, v in zip(ts, vs) if v is not None]
        return out

    def window(
        self,
        names: Iterable[str] | None = None,
        window_s: float | None = None,
    ) -> dict[str, list[tuple[float, float]]]:
        return {
            name: self.points(name, window_s)
            for name in (names if names is not None else self._cols)
            if name in self._cols
        }

    def encode(
        self,
        names: Iterable[str] | None = None,
        window_s: float | None = None,
    ) -> dict[str, dict]:
        """The compact query/wire form: per-series delta encodings."""
        return {
            name: delta_encode(pts, _precision(name))
            for name, pts in self.window(names, window_s).items()
        }
