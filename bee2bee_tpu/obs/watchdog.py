"""Trend watchdog: EWMA baseline + change-point detection over a TsRing.

Dependency-free detection of the two degradation shapes that matter
operationally, per curated series (obs/tsring.py):

- **slope**: the trailing window's least-squares slope, normalized to
  "fraction of the level per minute", exceeds the series threshold in
  its bad direction — a sinking peer caught while it is still sinking;
- **level_shift**: the trailing window's mean has departed the EWMA
  baseline by both a sigma multiple AND a relative fraction — a step
  change (acceptance collapse, queue cliff) too abrupt to read as slope.

The EWMA baseline/variance is **lagged**: it absorbs only samples old
enough to have left the detection window, so the anomaly being detected
cannot contaminate the baseline it is judged against.

A confirmed anomaly emits a typed ``trend:<series>`` incident into the
FlightRecorder (health.py) with the offending window attached, under a
per-series cooldown on the injected clock — deterministic in simnet
virtual time, which is what makes the seeded-collapse regression test
(tests/test_obs.py) able to pin the firing tick across same-seed runs.
"""

from __future__ import annotations

import math
from collections import deque
from dataclasses import dataclass, replace
from typing import Mapping

from ..clock import Clock, resolve_clock
from .tsring import DEFAULT_SCALE_FLOOR, SERIES_BY_NAME, TsRing

TREND_DIGEST_VERSION = 1


@dataclass(frozen=True)
class TrendPolicy:
    """Per-series detection thresholds (all per-series overridable).

    - ``slope_per_min``: relative slope (fraction of the level per
      minute) in the bad direction that counts as degradation.
    - ``level_sigma`` / ``level_frac``: a level shift must clear BOTH a
      baseline-sigma multiple and a relative fraction of the level —
      the sigma gate alone would alarm on any quiet series' first
      wiggle, the fraction gate alone on any noisy series forever.
    - ``window``: trailing samples examined for slope/window-mean.
    - ``min_baseline``: baseline samples absorbed before detection arms.
    - ``cooldown_s``: per-series incident spacing (on the clock seam;
      the recorder's own per-kind cooldown still applies underneath).
    """

    slope_per_min: float = 0.05
    level_sigma: float = 4.0
    level_frac: float = 0.25
    window: int = 12
    min_baseline: int = 6
    cooldown_s: float = 60.0
    ewma_alpha: float = 0.1


# series-tuned overrides on top of the dataclass defaults: acceptance
# and pool-occupancy move slowly by construction (cumulative-ish
# denominators), so their slope gates are tighter; RTT is jittery, so
# its level gate is looser.
DEFAULT_POLICIES: dict[str, TrendPolicy] = {
    "spec_acceptance": TrendPolicy(slope_per_min=0.03, level_frac=0.15),
    "pool_free_frac": TrendPolicy(slope_per_min=0.03),
    "peer_rtt_ms": TrendPolicy(level_sigma=6.0, level_frac=0.5),
}


class _SeriesState:
    __slots__ = ("ewma", "ewvar", "warm", "pending", "last_fire", "anom")

    def __init__(self, window: int):
        self.ewma: float | None = None
        self.ewvar = 0.0
        self.warm = 0
        # samples younger than the detection window, oldest first; they
        # graduate into the EWMA baseline as newer samples arrive
        self.pending: deque[tuple[float, float]] = deque(maxlen=window + 1)
        self.last_fire: float | None = None
        self.anom: dict | None = None


def _slope_per_s(points: list[tuple[float, float]]) -> float:
    """Ordinary least-squares slope of value over time (per second)."""
    n = len(points)
    if n < 2:
        return 0.0
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    num = sum((t - mt) * (v - mv) for t, v in points)
    den = sum((t - mt) ** 2 for t, _ in points)
    return num / den if den > 0 else 0.0


class TrendWatchdog:
    """Observe a TsRing after each sample; fire typed trend incidents.

    ``recorder=None`` resolves the process-global FlightRecorder at fire
    time (the singleton contract health.py documents); tests inject
    their own. ``node_id`` stamps incidents with the owning peer."""

    def __init__(
        self,
        ring: TsRing,
        policies: Mapping[str, TrendPolicy] | None = None,
        recorder=None,
        node_id: str | None = None,
        clock: Clock | None = None,
    ):
        self.ring = ring
        self.recorder = recorder
        self.node_id = node_id
        self._clock = resolve_clock(clock)
        base = dict(DEFAULT_POLICIES)
        if policies:
            base.update(policies)
        self.policies: dict[str, TrendPolicy] = {
            name: base.get(name, TrendPolicy()) for name in ring.series
        }
        self._state: dict[str, _SeriesState] = {
            name: _SeriesState(self.policies[name].window)
            for name in ring.series
        }

    def set_policy(self, name: str, **overrides) -> None:
        self.policies[name] = replace(self.policies[name], **overrides)

    # ------------------------------------------------------------ detection

    def observe(self) -> list[dict]:
        """Examine the ring's latest sample; returns the anomalies fired
        THIS call (already recorded as incidents). Call after append."""
        fired: list[dict] = []
        for name in self.ring.series:
            pts = self.ring.points(name)
            if not pts:
                continue
            st = self._state[name]
            pol = self.policies[name]
            last = pts[-1]
            if st.pending and st.pending[-1][0] >= last[0]:
                continue  # no new sample for this series (gap tick)
            st.pending.append(last)
            # graduate samples that aged out of the detection window
            while len(st.pending) > pol.window:
                _, old = st.pending.popleft()
                self._absorb(st, old, pol.ewma_alpha)
            anom = self._detect(name, st, pol)
            st.anom = anom
            if anom is not None and self._cooldown_ok(st, pol):
                st.last_fire = self._clock.time()
                self._fire(name, anom)
                fired.append(anom)
        return fired

    @staticmethod
    def _absorb(st: _SeriesState, v: float, alpha: float) -> None:
        if st.ewma is None:
            st.ewma, st.ewvar = v, 0.0
        else:
            d = v - st.ewma
            st.ewma += alpha * d
            st.ewvar = (1 - alpha) * (st.ewvar + alpha * d * d)
        st.warm += 1

    def _detect(self, name: str, st: _SeriesState, pol: TrendPolicy) -> dict | None:
        if st.ewma is None or st.warm < pol.min_baseline:
            return None
        if len(st.pending) < max(3, pol.window // 2):
            return None
        spec = SERIES_BY_NAME.get(name)
        up_bad = spec is None or spec.direction == "up_bad"
        floor = spec.scale_floor if spec is not None else DEFAULT_SCALE_FLOOR
        window = list(st.pending)
        mean = sum(v for _, v in window) / len(window)
        scale = max(abs(st.ewma), floor)
        sigma = math.sqrt(max(st.ewvar, 0.0))
        dev = mean - st.ewma
        bad_dev = dev if up_bad else -dev
        rel_slope = _slope_per_s(window) * 60.0 / scale
        bad_slope = rel_slope if up_bad else -rel_slope
        kind = None
        if bad_dev > pol.level_sigma * sigma and bad_dev >= pol.level_frac * scale:
            kind = "level_shift"
        elif bad_slope > pol.slope_per_min:
            kind = "slope"
        if kind is None:
            return None
        return {
            "series": name,
            "kind": kind,
            "baseline": round(st.ewma, 6),
            "baseline_sigma": round(sigma, 6),
            "window_mean": round(mean, 6),
            "slope_per_min": round(rel_slope, 6),
            "window": [[round(t, 3), round(v, 6)] for t, v in window],
        }

    def _cooldown_ok(self, st: _SeriesState, pol: TrendPolicy) -> bool:
        if st.last_fire is None:
            return True
        return self._clock.time() - st.last_fire >= pol.cooldown_s

    def _fire(self, name: str, anom: dict) -> None:
        rec = self.recorder
        if rec is None:
            from ..health import get_recorder  # late: singleton at fire time

            rec = get_recorder()
        try:
            rec.incident(
                "trend:" + name,
                detail=(
                    f"{anom['kind']}: window mean {anom['window_mean']} vs "
                    f"baseline {anom['baseline']} "
                    f"(slope {anom['slope_per_min']}/min)"
                ),
                node=self.node_id,
                extra=anom,
            )
        except Exception:  # noqa: BLE001 — telemetry never throws
            pass

    # ------------------------------------------------------------- digest

    def snapshot(self) -> dict[str, dict]:
        """The trend digest's ``series`` block: per-series window mean,
        relative slope (fraction of the level per minute, normalized by
        ``max(|window mean|, scale_floor)`` so receivers can recover an
        absolute slope), and the current anomaly flag."""
        out: dict[str, dict] = {}
        for name in self.ring.series:
            st = self._state[name]
            window = list(st.pending)
            if len(window) < 2:
                continue
            spec = SERIES_BY_NAME.get(name)
            floor = spec.scale_floor if spec is not None else DEFAULT_SCALE_FLOOR
            mean = sum(v for _, v in window) / len(window)
            rel = _slope_per_s(window) * 60.0 / max(abs(mean), floor)
            entry = {
                "mean": round(mean, 4),
                "slope": round(rel, 4),
                "n": len(window),
            }
            if st.anom is not None:
                entry["anom"] = 1
                entry["anom_kind"] = st.anom["kind"]
            out[name] = entry
        return out
