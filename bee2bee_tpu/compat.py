"""Version compatibility shims for the jax API surface.

THE one place cross-version differences are absorbed — call sites use the
newest API spelling and this module maps it onto older installs.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """jax.shard_map with the modern keyword surface on any jax.

    Newer jax exports ``jax.shard_map`` (replication checking flag named
    ``check_vma``); 0.4.x ships it as ``jax.experimental.shard_map`` with
    the flag named ``check_rep``. The two flags mean the same thing ONLY
    at the False setting (skip the static replication/varying-manual-axes
    check) — which is therefore the default and what every caller uses;
    the True settings differ in strictness across versions."""
    if hasattr(jax, "shard_map"):
        try:
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma,
            )
        except TypeError:
            # transition-band jax: top-level shard_map exists but the
            # flag is still named check_rep (the promotion landed before
            # the rename) — wrapping raises TypeError immediately, so
            # this fallback is hit at wrap time, not at trace time
            return jax.shard_map(
                f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                check_rep=check_vma,
            )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
