"""SLO-aware routing policy: score providers on live telemetry digests.

Replaces ``pick_provider``'s static "cheapest, then lowest-latency" sort
(the reference's rule, PAPER.md L3) with a scorer over the signals the
health plane already gossips fleet-wide (health.py digests on the ping
cadence):

- queue-wait p95 (``hist["engine.queue_wait_ms"]``) — requests already
  waiting there will wait in front of ours;
- batch-fill (``gauge["engine.batch_fill"]``) — headroom in the decode
  batch;
- paged-pool pressure (``engine.paged_blocks_free / _total``) — a nearly
  dry pool means admission backpressure is imminent;
- SLO burn state (the digest's ``slo`` brief) — a peer burning its error
  budget is EXCLUDED outright (sending it more traffic melts it faster),
  unless every candidate is excluded (degraded service beats none);
- RTT to the peer (the hello/ping bookkeeping) and price as weak signals;
- prompt-prefix locality (router/prefixmap.py): a peer advertising the
  prompt's leading-block hashes gets a bonus per matched block, so CoW
  prefix sharing actually gets hit across the mesh.

Scores are penalties — lower wins. Every signal is normalized to [0, 1]
via soft knees (``x / (x + ref)``) so one hot metric can't saturate the
sum. A peer with NO fresh digest scores the explicit **unknown tier**
(neutral 0.5 on the load signals) instead of the old ``_latency or 1e9``
sort key that permanently deprioritized never-pinged peers; when no
candidate has a fresh digest at all, the caller falls back to the legacy
static sort (meshnet/node.pick_provider keeps it).

Weights are env-tunable (``BEE2BEE_ROUTER``, inline JSON or a path) and
validated loudly, same contract as the SLO config.
"""

from __future__ import annotations

from dataclasses import dataclass, fields

from ..health import digest_slo_burn
from ..metrics import get_registry
from ..utils import load_json_source
from .prefixmap import match_depth, prompt_prefix_hashes

# routing decision counters: mode is a closed set, so cardinality is fixed
_C_DECISIONS = get_registry().counter(
    "router.decisions", "provider picks by mode (scored / static fallback)"
)
_C_PREFIX_PREFERRED = get_registry().counter(
    "router.prefix_preferred",
    "scored picks whose winner matched the prompt's prefix blocks",
)
_C_SLO_EXCLUDED = get_registry().counter(
    "router.slo_excluded", "candidates excluded for burning their SLO budget"
)
_C_DRAIN_EXCLUDED = get_registry().counter(
    "router.drain_excluded", "candidates excluded for draining"
)
_C_WARMUP_EXCLUDED = get_registry().counter(
    "router.warmup_excluded",
    "candidates excluded as standby/warming fleet replicas",
)
_C_ADAPTER_PREFERRED = get_registry().counter(
    "router.adapter_preferred",
    "scored picks whose winner already held the requested adapter",
)

MODE_SCORED = "scored"
MODE_STATIC = "static_fallback"


@dataclass(frozen=True)
class RouterWeights:
    """Penalty weights + normalization knees; lower total score wins."""

    queue: float = 0.30        # queue-wait p95 penalty weight
    fill: float = 0.25         # batch-fill penalty weight
    pool: float = 0.20         # paged-pool pressure penalty weight
    rtt: float = 0.10          # network distance penalty weight
    price: float = 0.05        # price tie-break weight
    prefix_bonus: float = 0.08  # score credit per matched prefix block
    prefix_max_blocks: int = 2  # cap on credited blocks ("within tolerance":
    # a prefix match may beat at most ~prefix_bonus*max/fill of batch-fill
    # difference, never a peer that is outright loaded)
    adapter_bonus: float = 0.12  # score credit for a peer whose digest
    # advertises the requested LoRA adapter resident (adapters/): routing
    # there skips a whole DHT piece fetch + pool churn. Like the prefix
    # bonus it is affinity-within-tolerance — burning/draining peers are
    # excluded BEFORE scoring, and the bonus stays below fill+pool
    # weights so residency never beats an outright-loaded node
    queue_ref_ms: float = 500.0  # soft knee: p95 at the knee scores 0.5
    rtt_ref_ms: float = 100.0
    unknown: float = 0.5       # the explicit unknown tier for digest-less peers
    # engine economics (digest `introspect` block, engine/introspect.py):
    # a memory-squeezed peer — HBM headroom under the floor — ramps a
    # penalty 0→1 as headroom falls to zero (peers without a ledger
    # reading pay nothing: absent subsystem, not unknown pressure), and
    # a peer reporting a recent retrace storm pays a flat penalty (its
    # next requests eat compile wall-time, the exact latency a router
    # exists to route around) — penalties, not exclusions: a degraded
    # engine still beats a burning or draining one
    hbm: float = 0.15
    hbm_headroom_floor: float = 0.10
    storm: float = 0.10
    # trend watchdog (obs/, ISSUE 20): a DEGRADING peer — queue-wait
    # sloping up or goodput sloping down in its gossiped trend digest —
    # pays a penalty that ramps with the relative slope and saturates
    # when the peer's own watchdog flags either series anomalous. A
    # penalty, not an exclusion: the point is to demote a sinking peer
    # BEFORE its SLO trips, and a mildly-degrading engine still beats a
    # burning or draining one. degrading_slope_ref is the relative slope
    # (fraction of the level per minute) that counts as fully degrading.
    degrading: float = 0.15
    degrading_slope_ref: float = 0.10


def parse_router_weights(obj) -> RouterWeights:
    if not isinstance(obj, dict):
        raise ValueError(f"router config must be a JSON object, got {type(obj).__name__}")
    known = {f.name for f in fields(RouterWeights)}
    unknown = set(obj) - known
    if unknown:
        raise ValueError(f"router config: unknown keys {sorted(unknown)}")
    kwargs = {}
    for k, v in obj.items():
        kwargs[k] = int(v) if k == "prefix_max_blocks" else float(v)
        if kwargs[k] < 0:
            raise ValueError(f"router config: {k} must be >= 0")
    return RouterWeights(**kwargs)


def load_router_weights(source: str | None = None) -> RouterWeights:
    """Weights from `source`, ``BEE2BEE_ROUTER`` (inline JSON or a path),
    or the defaults; malformed config raises at node construction."""
    data = load_json_source(source, "BEE2BEE_ROUTER")
    return parse_router_weights(data) if data is not None else RouterWeights()


def _soft(value: float, ref: float) -> float:
    """x/(x+ref): 0 at 0, 0.5 at the knee, asymptotically 1."""
    v = max(float(value), 0.0)
    return v / (v + ref) if ref > 0 else 1.0


def _slo_burning(digest: dict | None) -> bool:
    """True when the peer's own SLO brief reports any objective burning or
    tripped — the shed-before-melt contract seen from the outside. ONE
    rule shared with the fleet controller's aggregates
    (health.digest_slo_burn): the controller must scale on exactly the
    definition of "burning" the router excludes on."""
    return digest_slo_burn(digest)[1]


class RouterPolicy:
    """Scores ``list_providers()`` candidates against HealthStore digests;
    ``pick`` returns the winner or None."""

    def __init__(self, weights: RouterWeights | None = None):
        self.weights = weights or load_router_weights()

    # ------------------------------------------------------------- scoring

    def score(self, cand: dict, digest: dict | None, rtt_ms: float | None,
              max_price: float, prompt_hashes: list[str],
              adapter: str | None = None) -> tuple[float, dict]:
        """(penalty score, breakdown) for one candidate. ``digest`` is the
        peer's fresh telemetry digest (the node's own live digest for the
        local candidate); None selects the unknown tier. ``adapter``
        credits peers whose digest advertises that LoRA adapter resident."""
        w = self.weights
        adapter_resident = bool(
            adapter
            and digest is not None
            and any(
                adapter in names
                for names in (digest.get("adapters") or {}).values()
                if isinstance(names, (list, tuple))
            )
        )
        hbm = 0.0
        storming = False
        degrading = 0.0
        if digest is None:
            queue = fill = pool = w.unknown
            matched = 0
        else:
            hist = digest.get("hist") or {}
            qw = hist.get("engine.queue_wait_ms") or {}
            queue = _soft(qw.get("p95") or 0.0, w.queue_ref_ms)
            gauge = digest.get("gauge") or {}
            # absent batch-fill/pool gauges mean the subsystem isn't
            # running (health.build_digest contract) — no pressure, not
            # unknown pressure
            fill = min(max(float(gauge.get("engine.batch_fill") or 0.0), 0.0), 1.0)
            total = float(gauge.get("engine.paged_blocks_total") or 0.0)
            if total > 0:
                free = float(gauge.get("engine.paged_blocks_free") or 0.0)
                pool = 1.0 - min(max(free / total, 0.0), 1.0)
            else:
                pool = 0.0
            matched = min(
                match_depth(prompt_hashes, digest.get("prefix_hashes")),
                w.prefix_max_blocks,
            )
            intro = digest.get("introspect") or {}
            headroom = (intro.get("hbm") or {}).get("headroom_frac")
            if headroom is not None and w.hbm_headroom_floor > 0:
                hbm = min(
                    max(
                        (w.hbm_headroom_floor - float(headroom))
                        / w.hbm_headroom_floor,
                        0.0,
                    ),
                    1.0,
                )
            storming = bool(intro.get("storming"))
            # trend digest (obs/): relative slopes, fraction of the
            # level per minute. Rising queue wait and falling goodput
            # are the two "sinking peer" signatures; either series
            # flagged anomalous by the peer's own watchdog saturates
            # the penalty. Absent trend block = absent subsystem = no
            # penalty (same contract as every other digest signal).
            tser = (digest.get("trend") or {}).get("series") or {}
            q_trend = tser.get("queue_wait_p95_ms") or {}
            g_trend = tser.get("goodput_tok_s") or {}
            try:
                bad_slope = max(float(q_trend.get("slope") or 0.0), 0.0) + \
                    max(-float(g_trend.get("slope") or 0.0), 0.0)
            except (TypeError, ValueError):
                bad_slope = 0.0
            if w.degrading_slope_ref > 0:
                degrading = min(bad_slope / w.degrading_slope_ref, 1.0)
            if q_trend.get("anom") or g_trend.get("anom"):
                degrading = 1.0
        rtt = 0.0 if cand.get("local") else (
            _soft(rtt_ms, w.rtt_ref_ms) if rtt_ms is not None else w.unknown
        )
        price = float(cand.get("price_per_token") or 0.0)
        pnorm = price / max_price if max_price > 0 else 0.0
        score = (
            w.queue * queue + w.fill * fill + w.pool * pool
            + w.rtt * rtt + w.price * pnorm
            + w.hbm * hbm + (w.storm if storming else 0.0)
            + w.degrading * degrading
            - w.prefix_bonus * matched
            - (w.adapter_bonus if adapter_resident else 0.0)
        )
        return score, {
            "queue": round(queue, 4), "fill": round(fill, 4),
            "pool": round(pool, 4), "rtt": round(rtt, 4),
            "price": round(pnorm, 4), "prefix_blocks": matched,
            "adapter_resident": adapter_resident,
            "hbm": round(hbm, 4), "storming": storming,
            "degrading": round(degrading, 4),
            "unknown": digest is None, "score": round(score, 4),
        }

    # --------------------------------------------------------------- pick

    def pick(
        self,
        candidates: list[dict],
        fresh_digests: dict[str, dict],
        local_digest: dict | None = None,
        prompt: str | None = None,
        adapter: str | None = None,
    ) -> tuple[dict | None, dict]:
        """Pick from candidates using fresh digests; returns
        ``(winner | None, decision)``. The caller handles the no-fresh-
        digest case (static fallback) — this method assumes scoring is
        worthwhile, i.e. at least one candidate has a digest."""
        ph = prompt_prefix_hashes(prompt)
        max_price = max(
            (float(c.get("price_per_token") or 0.0) for c in candidates),
            default=0.0,
        )
        scored: list[tuple[float, int, dict, dict]] = []
        excluded = 0
        for i, cand in enumerate(candidates):
            digest = (
                local_digest if cand.get("local")
                else fresh_digests.get(cand.get("provider_id"))
            )
            if digest is not None and digest.get("draining"):
                # a draining peer is LEAVING: its admission 503s every
                # new request anyway — unlike the SLO exclusion below,
                # there is no all-burning waiver back in (routing to it
                # just converts one hop into a guaranteed typed shed)
                _C_DRAIN_EXCLUDED.inc()
                continue
            if digest is not None and digest.get("fleet_state") in (
                "standby", "warming"
            ):
                # an elastic-fleet standby/warming replica (fleet/) has
                # NOT passed its warm-up probe: it must never receive
                # routed traffic — no waiver, same as draining (the
                # controller's probe is the only thing allowed to hit it)
                _C_WARMUP_EXCLUDED.inc()
                continue
            if _slo_burning(digest):
                excluded += 1
                _C_SLO_EXCLUDED.inc()
                continue
            s, breakdown = self.score(
                cand, digest, cand.get("_latency"), max_price, ph,
                adapter=adapter,
            )
            # deterministic tie-break: local first, then provider id
            scored.append((s, i, cand, breakdown))
        if not scored and excluded:
            # every candidate is burning: serve SOMEWHERE — degraded
            # routing beats a routable-provider deadlock (draining peers
            # stay out even here: they reject typed regardless)
            for i, cand in enumerate(candidates):
                digest = (
                    local_digest if cand.get("local")
                    else fresh_digests.get(cand.get("provider_id"))
                )
                if digest is not None and (
                    digest.get("draining")
                    or digest.get("fleet_state") in ("standby", "warming")
                ):
                    continue
                s, breakdown = self.score(
                    cand, digest, cand.get("_latency"), max_price, ph,
                    adapter=adapter,
                )
                breakdown["slo_override"] = True
                scored.append((s, i, cand, breakdown))
        if not scored:
            return None, {"mode": MODE_SCORED, "candidates": 0}
        scored.sort(key=lambda t: (
            t[0], not t[2].get("local"), str(t[2].get("provider_id"))
        ))
        best_score, _, winner, breakdown = scored[0]
        _C_DECISIONS.inc(mode=MODE_SCORED)
        if breakdown.get("prefix_blocks"):
            _C_PREFIX_PREFERRED.inc()
        if breakdown.get("adapter_resident"):
            _C_ADAPTER_PREFERRED.inc()
        return winner, {
            "mode": MODE_SCORED,
            "candidates": len(candidates),
            "slo_excluded": excluded,
            "winner": winner.get("provider_id"),
            "breakdown": breakdown,
        }


def static_sort(candidates: list[dict]) -> dict | None:
    """The legacy sort (reference p2p_runtime.py:744-746): cheapest, then
    lowest-latency, local as zero latency. Kept as the explicit fallback
    for when no telemetry digest is fresh — with its known stale-latency
    wart (``or 1e9`` deprioritizes never-pinged peers) contained to the
    no-telemetry regime where nothing better is knowable."""
    if not candidates:
        return None
    _C_DECISIONS.inc(mode=MODE_STATIC)
    return sorted(
        candidates,
        key=lambda p: (
            p.get("price_per_token") or 0.0,
            0.0 if p.get("local") else (p.get("_latency") or 1e9),
        ),
    )[0]
