"""Tenant identity and per-tenant serving policy.

One declarative config maps API keys to tenant names and carries each
tenant's fairness weight and token budget; the SAME config feeds every
consumer — API-key resolution (api.py auth), the admission controller's
WDRR weights and token buckets (router/admission.py), and the engine
scheduler's tenant queues (engine/scheduler.py) — so a weight change
cannot drift between layers.

Config source: ``BEE2BEE_TENANTS`` (inline JSON object or a path to one),
validated loudly at load like ``BEE2BEE_SLO_CONFIG`` — a mis-typed tenant
config must fail the node at construction, not silently rate-limit the
wrong customer later. Shape::

    {"acme":  {"api_key": "k-acme", "weight": 4,
               "rate_tokens_per_min": 60000},
     "hobby": {"api_key": "k-hobby", "weight": 1}}

Unconfigured identity clamps to the ``default`` tenant (weight 1, no
budget): tenant names become METRIC LABELS and WDRR queue keys, so the
set must stay bounded by configuration, never by what a peer or client
claims on the wire.
"""

from __future__ import annotations

import hmac
from dataclasses import dataclass

from ..utils import load_json_source

DEFAULT_TENANT = "default"


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's identity + serving policy."""

    name: str
    api_key: str | None = None
    weight: float = 1.0
    # token budget: sustained refill rate (0 = unlimited) and burst size
    # (0 = one minute of sustained rate)
    rate_tokens_per_min: float = 0.0
    burst_tokens: float = 0.0
    # default LoRA adapter (adapters/): a request from this tenant that
    # names no adapter (plain base model id, no `adapter` field) serves
    # under this one — the one-base-many-tenants mapping. None = base.
    adapter: str | None = None

    @property
    def rate_tokens_per_s(self) -> float:
        return self.rate_tokens_per_min / 60.0

    @property
    def burst(self) -> float:
        return self.burst_tokens or self.rate_tokens_per_min


_ALLOWED_KEYS = frozenset(
    {"api_key", "weight", "rate_tokens_per_min", "burst_tokens", "adapter"}
)


def parse_tenant_config(obj) -> dict[str, TenantSpec]:
    """Validate a {name: spec} mapping; raises ValueError on junk."""
    if not isinstance(obj, dict):
        raise ValueError(f"tenant config must be a JSON object, got {type(obj).__name__}")
    out: dict[str, TenantSpec] = {}
    seen_keys: set[str] = set()
    for name, spec in obj.items():
        if not name or not isinstance(spec, dict):
            raise ValueError(f"tenant {name!r}: spec must be an object")
        unknown = set(spec) - _ALLOWED_KEYS
        if unknown:
            raise ValueError(f"tenant {name!r}: unknown keys {sorted(unknown)}")
        weight = float(spec.get("weight", 1.0))
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        rate = float(spec.get("rate_tokens_per_min", 0.0))
        burst = float(spec.get("burst_tokens", 0.0))
        if rate < 0 or burst < 0:
            raise ValueError(f"tenant {name!r}: budgets must be >= 0")
        key = spec.get("api_key")
        if key is not None:
            key = str(key)
            if key in seen_keys:
                # key → tenant resolution would be ambiguous: the first
                # match would silently absorb the second tenant's traffic
                raise ValueError(f"tenant {name!r}: api_key reused by another tenant")
            seen_keys.add(key)
        adapter = spec.get("adapter")
        if adapter is not None:
            from ..adapters import clamp_adapter_name

            if clamp_adapter_name(str(adapter)) is None:
                # same clamp as the wire: a malformed default would turn
                # every request from this tenant into a typed 404
                raise ValueError(
                    f"tenant {name!r}: invalid adapter name {adapter!r}"
                )
            adapter = str(adapter)
        out[str(name)] = TenantSpec(
            name=str(name), api_key=key, weight=weight,
            rate_tokens_per_min=rate, burst_tokens=burst,
            adapter=adapter,
        )
    return out


def load_tenant_config(source: str | None = None) -> dict[str, TenantSpec]:
    """Tenant specs from `source`, the ``BEE2BEE_TENANTS`` env var (inline
    JSON object, or a path to a JSON file), or empty (no tenants)."""
    data = load_json_source(source, "BEE2BEE_TENANTS")
    return parse_tenant_config(data) if data is not None else {}


class TenantRegistry:
    """Resolved tenant table: API-key → name, weights, budgets."""

    def __init__(self, specs: dict[str, TenantSpec] | None = None):
        self.specs = dict(specs or {})

    def resolve_key(self, api_key: str | None) -> str | None:
        """Tenant name for a presented API key (constant-time compares —
        the key is the SDK-facing credential), or None when no tenant
        claims it."""
        if not api_key:
            return None
        enc = lambda s: s.encode("utf-8", "surrogateescape")
        for spec in self.specs.values():
            if spec.api_key and hmac.compare_digest(enc(api_key), enc(spec.api_key)):
                return spec.name
        return None

    def api_keys(self) -> list[str]:
        return [s.api_key for s in self.specs.values() if s.api_key]

    def clamp(self, name) -> str:
        """Wire-supplied tenant claim → a configured name or ``default``.
        Tenant names key metric labels and WDRR queues; an unconfigured
        claim must not mint a new series."""
        if isinstance(name, str) and name in self.specs:
            return name
        return DEFAULT_TENANT

    def weight(self, name: str) -> float:
        spec = self.specs.get(name)
        return spec.weight if spec else 1.0

    def default_adapter(self, name: str) -> str | None:
        """The tenant's configured default LoRA adapter (adapters/), or
        None for the base model. Applied only when the request itself
        names no adapter — an explicit model="<base>:<name>" wins."""
        spec = self.specs.get(name)
        return spec.adapter if spec else None

    def weights(self) -> dict[str, float]:
        return {name: s.weight for name, s in self.specs.items()}

    def budgets(self) -> dict[str, tuple[float, float]]:
        """{tenant: (rate tokens/s, burst tokens)} for budgeted tenants."""
        return {
            name: (s.rate_tokens_per_s, s.burst)
            for name, s in self.specs.items()
            if s.rate_tokens_per_min > 0
        }
