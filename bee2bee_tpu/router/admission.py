"""Admission control: bounded queueing, per-tenant budgets, typed shedding.

The front door's backpressure layer, sitting at both ingress surfaces
(api.py HTTP handlers and meshnet/node._serve_gen_request p2p serving).
Every local generation acquires a slot first; when the node is saturated,
requests wait in a **weighted deficit-round-robin** queue keyed by tenant
(router/fairness.py), so one tenant's burst cannot starve another past
its weight. Rejections are TYPED — the caller always learns which
contract it hit and when to come back:

- ``429`` + ``rate_limited``      — the tenant's token budget is spent
  (token bucket; ``Retry-After`` = time until the bucket covers the ask);
- ``429`` + ``tenant_queue_full`` — the tenant already has its fair share
  of waiters queued (per-tenant bound — a fairness rejection, not a node
  overload);
- ``503`` + ``queue_full``        — the node-wide waiter bound is hit;
- ``503`` + ``queue_timeout``     — a waiter aged out before a slot freed
  (the no-request-hangs contract);
- ``503`` + ``pool_exhausted``    — the paged KV pool is nearly dry while
  every slot is busy (admission would only park the request on scheduler
  backpressure);
- ``503`` + ``slo_shed``          — this node's SLO fast window is burning
  (health.SloTracker): shed BEFORE the node melts, while peers with
  budget left absorb the traffic (the router stops picking a burning
  node, so shedding and routing converge).

Everything runs on the node's event loop: no locks, no threads. Config
via ``BEE2BEE_ADMISSION`` (inline JSON or a path), validated loudly.
"""

from __future__ import annotations

import asyncio
import math
from dataclasses import dataclass, fields

from ..clock import Clock, get_clock, resolve_clock
from ..metrics import get_registry
from ..utils import load_json_source
from .fairness import WdrrQueue

# admission observability (bee2bee_admission_* after prefixing): outcome/
# kind are closed sets; tenant is bounded by configuration (TenantRegistry
# clamps wire claims to configured names + "default")
_C_REQUESTS = get_registry().counter(
    "admission.requests", "admission outcomes by kind"
)
_C_SHED = get_registry().counter(
    "admission.shed", "requests shed with a typed 429/503 by kind"
)
_C_TENANT_TOKENS = get_registry().counter(
    "admission.tenant_tokens", "completed generation tokens by tenant"
)
_G_INFLIGHT = get_registry().gauge(
    "admission.inflight", "generations holding an admission slot"
)
_G_QUEUED = get_registry().gauge(
    "admission.queued", "requests waiting for an admission slot"
)

KIND_RATE = "rate_limited"
KIND_TENANT_QUEUE = "tenant_queue_full"
KIND_QUEUE = "queue_full"
KIND_TIMEOUT = "queue_timeout"
KIND_POOL = "pool_exhausted"
KIND_SLO = "slo_shed"
KIND_DRAINING = "draining"

# 429: the CALLER's contract (its budget, its share of the queue);
# 503: the NODE's state (overload, pool, SLO, drain) — retry elsewhere/later
_STATUS = {
    KIND_RATE: 429,
    KIND_TENANT_QUEUE: 429,
    KIND_QUEUE: 503,
    KIND_TIMEOUT: 503,
    KIND_POOL: 503,
    KIND_SLO: 503,
    KIND_DRAINING: 503,
}


class AdmissionReject(RuntimeError):
    """Typed admission rejection; carries everything a 429/503 response
    (or a GEN_ERROR frame) needs: kind, HTTP status, Retry-After."""

    def __init__(self, kind: str, retry_after_s: float, detail: str = ""):
        super().__init__(detail or f"admission rejected: {kind}")
        self.kind = kind
        self.status = _STATUS.get(kind, 503)
        self.retry_after_s = round(max(float(retry_after_s), 0.0), 3)
        self.detail = detail or f"admission rejected: {kind}"


@dataclass(frozen=True)
class AdmissionConfig:
    max_concurrent: int = 32      # in-flight generations (slots)
    max_queue: int = 128          # node-wide waiter bound
    tenant_queue: int = 64        # per-tenant waiter bound
    queue_timeout_s: float = 60.0  # max wait for a slot (no request hangs)
    shed_burn_rate: float = 6.0   # SLO fast-window burn that starts shedding
    pool_free_frac_min: float = 0.02  # paged free fraction under which we shed
    pool_eta_shed_s: float = 5.0  # shed when the pool-growth forecast
    # (engine.pool_exhaust_eta_s) projects exhaustion inside this horizon
    # while all slots are busy; 0 disables the forecast shed
    retry_after_s: float = 1.0    # base Retry-After hint for queue rejections
    shed_retry_after_s: float = 5.0  # Retry-After for node-state (503) sheds
    quantum: float = 256.0        # WDRR quantum (tokens)


def parse_admission_config(obj) -> AdmissionConfig:
    if not isinstance(obj, dict):
        raise ValueError(
            f"admission config must be a JSON object, got {type(obj).__name__}"
        )
    known = {f.name for f in fields(AdmissionConfig)}
    unknown = set(obj) - known
    if unknown:
        raise ValueError(f"admission config: unknown keys {sorted(unknown)}")
    kwargs = {}
    for k, v in obj.items():
        kwargs[k] = (
            int(v) if k in ("max_concurrent", "max_queue", "tenant_queue")
            else float(v)
        )
        if kwargs[k] <= 0 and k in ("max_concurrent", "quantum"):
            raise ValueError(f"admission config: {k} must be > 0")
        if kwargs[k] < 0:
            raise ValueError(f"admission config: {k} must be >= 0")
    return AdmissionConfig(**kwargs)


def load_admission_config(source: str | None = None) -> AdmissionConfig:
    """Config from `source`, ``BEE2BEE_ADMISSION`` (inline JSON or a
    path), or the defaults; malformed config fails node construction."""
    data = load_json_source(source, "BEE2BEE_ADMISSION")
    return parse_admission_config(data) if data is not None else AdmissionConfig()


def paged_pool_free_fraction() -> float | None:
    """Free fraction of the paged KV pool from the local registry gauges,
    or None when no paged engine runs in this process."""
    reg = get_registry()
    total = reg.get("engine.paged_blocks_total")
    free = reg.get("engine.paged_blocks_free")
    try:
        if total is None or free is None or not total.series():
            return None
        t = total.value()
        if t <= 0:
            return None
        return max(0.0, min(free.value() / t, 1.0))
    except Exception:  # noqa: BLE001 — a telemetry read must not shed traffic
        return None


def pool_exhaust_eta() -> float | None:
    """Projected seconds to paged-pool exhaustion from the forecast gauge
    (engine/introspect.py PoolForecast), or None when the pool is not
    growing / no paged engine runs here. Registry-read like
    paged_pool_free_fraction, so the front door needs no engine import."""
    reg = get_registry()
    g = reg.get("engine.pool_exhaust_eta_s")
    try:
        if g is None or not g.series():
            return None
        return float(g.value())
    except Exception:  # noqa: BLE001 — a telemetry read must not shed traffic
        return None


class _TokenBucket:
    """Sustained-rate token budget with burst capacity."""

    def __init__(self, rate_per_s: float, burst: float, now=None):
        self.rate = float(rate_per_s)
        self.burst = max(float(burst), 1.0)
        self._now = now if now is not None else (lambda: get_clock().monotonic())
        self._tokens = self.burst
        self._t = now()

    def _refill(self) -> None:
        t = self._now()
        self._tokens = min(self.burst, self._tokens + (t - self._t) * self.rate)
        self._t = t

    def take(self, n: float) -> bool:
        # an ask larger than the burst clamps to it: the request charges
        # (and, on rejection, waits for) the WHOLE burst — heavy but
        # SATISFIABLE. Without the clamp a default-sized ask against a
        # small burst is permanently unsatisfiable yet rejected with a
        # finite Retry-After, and well-behaved clients retry forever.
        n = min(float(n), self.burst)
        self._refill()
        if self._tokens >= n:
            self._tokens -= n
            return True
        return False

    def refund(self, n: float) -> None:
        """Return tokens charged for work that never ran (queue timeout,
        cancelled waiter): overload must not convert into a spurious
        rate-limit lockout once the node recovers. The same burst clamp
        as take(), so a refund restores exactly what was charged."""
        self._refill()
        self._tokens = min(
            self.burst, self._tokens + min(max(float(n), 0.0), self.burst)
        )

    def eta_s(self, n: float) -> float:
        """Seconds until the bucket could cover n tokens."""
        self._refill()
        if self._tokens >= n:
            return 0.0
        if self.rate <= 0:
            return math.inf
        return (min(n, self.burst) - self._tokens) / self.rate


class _Waiter:
    __slots__ = ("fut", "tenant", "cost", "abandoned")

    def __init__(self, fut: asyncio.Future, tenant: str, cost: float = 1.0):
        self.fut = fut
        self.tenant = tenant
        self.cost = cost
        # set by the abandoning acquire (timeout / caller cancellation),
        # which ALSO removes the waiter from the queue-bound counters —
        # _dispatch must then skip it without double-decrementing
        self.abandoned = False


class AdmissionTicket:
    """One admitted generation's slot; release exactly once (idempotent).
    Usable as an async context manager."""

    def __init__(self, ctrl: "AdmissionController", tenant: str):
        self._ctrl = ctrl
        self.tenant = tenant
        self._released = False

    def note_tokens(self, n: int) -> None:
        """Completed-token accounting (the fairness bench's measurement)."""
        self._ctrl.note_tokens(self.tenant, n)

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ctrl._release()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        self.release()


class AdmissionController:
    """Slot-bounded admission with WDRR tenant queues and typed rejects.

    ``slo_burn`` / ``pool_free_fraction`` are injected callables so the
    controller stays testable without a node (and so a node wires its OWN
    SloTracker, not process-global state)."""

    def __init__(
        self,
        config: AdmissionConfig | None = None,
        weights: dict[str, float] | None = None,
        budgets: dict[str, tuple[float, float]] | None = None,
        slo_burn=None,
        pool_free_fraction=None,
        pool_eta=None,  # callable -> float | None: projected seconds to
        # paged-pool exhaustion (engine/introspect.py PoolForecast) —
        # sheds pool_exhausted BEFORE the free-fraction floor trips
        draining=None,  # callable -> bool: node drain state (migrate.py);
        # True rejects every new acquisition 503 `draining` + Retry-After
        now=None,
        clock: Clock | None = None,  # time seam (clock.py): queue
        # timeouts + token buckets follow the node's injected clock; an
        # explicit `now` callable still wins for bucket tests
    ):
        self.config = config or AdmissionConfig()
        self._clock = resolve_clock(clock)
        if now is None:
            now = self._clock.monotonic
        self._buckets = {
            t: _TokenBucket(rate, burst, now)
            for t, (rate, burst) in (budgets or {}).items()
        }
        self._slo_burn = slo_burn
        self._pool_free = pool_free_fraction
        self._pool_eta = pool_eta
        self._draining = draining
        self._free = int(self.config.max_concurrent)
        self._waiters = WdrrQueue(weights or {}, quantum=self.config.quantum)
        self._queued_total = 0
        self._queued_by_tenant: dict[str, int] = {}
        self.tenant_tokens: dict[str, float] = {}  # bench/debug view

    # ------------------------------------------------------------- metrics

    def note_tokens(self, tenant: str, n) -> None:
        try:
            n = float(n)
        except (TypeError, ValueError):
            return
        if n > 0:
            self.tenant_tokens[tenant] = self.tenant_tokens.get(tenant, 0.0) + n
            _C_TENANT_TOKENS.inc(n, tenant=tenant)

    def _reject(self, kind: str, retry_after_s: float, detail: str = ""):
        _C_REQUESTS.inc(outcome="rejected", kind=kind)
        _C_SHED.inc(kind=kind)
        raise AdmissionReject(kind, retry_after_s, detail)

    # ------------------------------------------------------------- acquire

    @property
    def inflight(self) -> int:
        return int(self.config.max_concurrent) - self._free

    @property
    def queued(self) -> int:
        return self._queued_total

    def _check_shed(self, migration: bool = False) -> None:
        cfg = self.config
        if self._draining is not None and self._draining():
            # draining precedes every other check: the node is leaving —
            # in-flight generations migrate out, new work goes elsewhere
            # (and it must not ACCEPT migrations while exporting its own)
            self._reject(
                KIND_DRAINING, cfg.shed_retry_after_s,
                "node is draining; retry against another peer",
            )
        if not migration and self._slo_burn is not None:
            # migration imports skip ONLY this clause: evacuated state
            # must land somewhere, the exporter's router already
            # deprioritizes burning peers, and the pool/queue bounds
            # below still protect the target
            burn = self._slo_burn()
            if burn is not None and burn >= cfg.shed_burn_rate:
                self._reject(
                    KIND_SLO, cfg.shed_retry_after_s,
                    f"SLO fast window burning at {burn:.1f}x budget "
                    f"(shed threshold {cfg.shed_burn_rate:g}x)",
                )
        if self._pool_free is not None and self._free <= 0:
            # pool pressure only sheds when every slot is busy too: a dry
            # pool with idle slots means retirements are freeing blocks
            frac = self._pool_free()
            if frac is not None and frac < cfg.pool_free_frac_min:
                self._reject(
                    KIND_POOL, cfg.shed_retry_after_s,
                    f"paged KV pool {frac * 100:.1f}% free "
                    f"(< {cfg.pool_free_frac_min * 100:.1f}%) with all "
                    "slots busy",
                )
        if (self._pool_eta is not None and self._free <= 0
                and cfg.pool_eta_shed_s > 0):
            # growth FORECAST (engine/introspect.py): the pool may still
            # be above the free floor, but at the current allocation rate
            # it runs dry inside the horizon — shed now, while the
            # Retry-After still means something (all-slots-busy guarded
            # like the floor check: with idle slots, retirements free
            # blocks faster than the trend says)
            eta = self._pool_eta()
            if eta is not None and eta < cfg.pool_eta_shed_s:
                self._reject(
                    KIND_POOL, cfg.shed_retry_after_s,
                    f"paged KV pool projected dry in {eta:.1f}s "
                    f"(< {cfg.pool_eta_shed_s:g}s horizon) with all "
                    "slots busy",
                )

    def _charge_budget(self, tenant: str, cost_tokens: float) -> None:
        bucket = self._buckets.get(tenant)
        if bucket is not None and not bucket.take(cost_tokens):
            eta = bucket.eta_s(cost_tokens)
            self._reject(
                KIND_RATE,
                self.config.retry_after_s if math.isinf(eta) else eta,
                f"tenant {tenant!r} token budget exhausted "
                f"({cost_tokens:g} tokens asked)",
            )

    def _unqueue(self, tenant: str) -> None:
        """Remove one waiter from the queue-bound counters."""
        self._queued_total = max(0, self._queued_total - 1)
        left = self._queued_by_tenant.get(tenant, 1) - 1
        if left <= 0:
            self._queued_by_tenant.pop(tenant, None)
        else:
            self._queued_by_tenant[tenant] = left
        _G_QUEUED.set(self._queued_total)

    def _refund_budget(self, tenant: str, cost: float) -> None:
        bucket = self._buckets.get(tenant)
        if bucket is not None:
            bucket.refund(cost)

    async def acquire(self, tenant: str = "default",
                      cost_tokens: float = 1.0,
                      migration: bool = False) -> AdmissionTicket:
        """Admit one generation (await a slot if saturated) or raise a
        typed AdmissionReject. ``cost_tokens`` is the request's token ask
        (max_new_tokens) — the unit budgets and WDRR fairness run in.
        ``migration`` marks a KV import (meshnet/migrate.py): it is not
        new demand but state being EVACUATED, so the SLO shed does not
        apply — draining, queue and pool bounds still do."""
        tenant = str(tenant or "default")
        cost = max(float(cost_tokens), 1.0)
        self._check_shed(migration=migration)
        if self._free > 0 and self._queued_total == 0:
            self._charge_budget(tenant, cost)
            self._free -= 1
            _C_REQUESTS.inc(outcome="admitted", kind="ok")
            _G_INFLIGHT.set(self.inflight)
            return AdmissionTicket(self, tenant)
        # saturated: queue under WDRR, bounded per tenant and node-wide.
        # Capacity rejections come BEFORE the budget charge — a request
        # the queue bounds turn away must not spend its tenant's tokens.
        cfg = self.config
        if self._queued_by_tenant.get(tenant, 0) >= cfg.tenant_queue:
            self._reject(
                KIND_TENANT_QUEUE, cfg.retry_after_s,
                f"tenant {tenant!r} already has {cfg.tenant_queue} "
                "requests waiting",
            )
        if self._queued_total >= cfg.max_queue:
            self._reject(
                KIND_QUEUE, cfg.retry_after_s,
                f"admission queue full ({cfg.max_queue} waiting)",
            )
        self._charge_budget(tenant, cost)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        w = _Waiter(fut, tenant, cost)
        self._waiters.append(w, tenant=tenant, cost=cost)
        self._queued_total += 1
        self._queued_by_tenant[tenant] = self._queued_by_tenant.get(tenant, 0) + 1
        _G_QUEUED.set(self._queued_total)
        try:
            await self._clock.wait_for(fut, cfg.queue_timeout_s)
        except asyncio.TimeoutError:
            # the abandoning side owns the bookkeeping: counts come off
            # NOW (a stalled node must not reject new arrivals against a
            # queue of dead waiters) and the charged budget is refunded
            # (the work never ran). _dispatch skips the cancelled record
            # when it eventually pops it.
            w.abandoned = True
            self._unqueue(tenant)
            self._refund_budget(tenant, cost)
            self._reject(
                KIND_TIMEOUT, cfg.retry_after_s,
                f"no execution slot freed within {cfg.queue_timeout_s:g}s",
            )
        except asyncio.CancelledError:
            w.abandoned = True
            if fut.done() and not fut.cancelled():
                # granted between the caller's cancellation and this frame
                # resuming: _dispatch already uncounted it and took the
                # slot — hand the slot straight back, and refund the
                # budget like every other work-never-ran path (cancel
                # storms must not convert into a rate-limit lockout)
                self._refund_budget(tenant, cost)
                self._release()
            else:
                self._unqueue(tenant)
                self._refund_budget(tenant, cost)
            raise
        _C_REQUESTS.inc(outcome="admitted", kind="ok")
        return AdmissionTicket(self, tenant)

    # ------------------------------------------------------------- release

    def _release(self) -> None:
        self._free += 1
        self._dispatch()
        _G_INFLIGHT.set(self.inflight)

    def _dispatch(self) -> None:
        """Hand freed slots to waiters in WDRR order, skipping abandoned
        (timed-out / cancelled) records — their counters were already
        removed by the abandoning acquire."""
        while self._free > 0 and self._waiters:
            w = self._waiters.popleft()
            if w.fut.cancelled() or w.abandoned:
                # popping charged the tenant's WDRR deficit for work that
                # never ran — give it back, or timeouts concentrated on
                # one tenant would push its share below its weight
                self._waiters.refund(w.tenant, w.cost)
                continue
            self._unqueue(w.tenant)
            self._free -= 1
            w.fut.set_result(None)
        _G_INFLIGHT.set(self.inflight)
