"""Weighted deficit round robin over per-tenant FIFO queues.

The fairness primitive shared by the two places requests queue: the
admission controller's waiter queue (router/admission.py, asyncio) and
the engine scheduler's submit queue (engine/scheduler.py, its own
thread). ``WdrrQueue`` is deliberately synchronization-free — each owner
already serializes access (the scheduler under its condition variable,
the admission controller on the event loop), and a lock here would just
be a second one.

DRR semantics (Shreedhar & Varghese): each tenant queue holds a deficit
counter; a full rotation over non-empty queues tops every deficit up by
``quantum * weight``, and a queue may dequeue its head once the deficit
covers the head's cost. Cost here is the request's token budget
(``max_new_tokens``), so fairness is in TOKENS, not request count — a
tenant asking for 10x longer generations gets proportionally fewer slots.
Long-run service ratio converges to the weight ratio whenever both
tenants keep their queues non-empty (the saturation regime the
``router_fairness`` bench rung drives).

A deficit resets when its queue drains: an idle tenant must not bank
credit and then burst past its weight when it returns.
"""

from __future__ import annotations

from collections import OrderedDict, deque

DEFAULT_QUANTUM = 256.0


class WdrrQueue:
    """Deque-compatible facade (append/appendleft/popleft/len/iter/clear)
    over per-tenant FIFOs with weighted-deficit dequeue order."""

    def __init__(self, weights: dict[str, float] | None = None,
                 quantum: float = DEFAULT_QUANTUM):
        self.quantum = float(quantum)
        self._weights = {str(k): float(v) for k, v in (weights or {}).items()}
        self._queues: OrderedDict[str, deque] = OrderedDict()
        self._deficit: dict[str, float] = {}

    def set_weights(self, weights: dict[str, float]) -> None:
        self._weights = {str(k): float(v) for k, v in (weights or {}).items()}

    def weight(self, tenant: str) -> float:
        return max(float(self._weights.get(tenant, 1.0)), 1e-6)

    # ------------------------------------------------------------- enqueue

    def _queue_for(self, tenant: str) -> deque:
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = deque()
            self._deficit.setdefault(tenant, 0.0)
        return q

    def append(self, item, tenant: str = "default", cost: float = 1.0) -> None:
        self._queue_for(tenant).append((item, max(float(cost), 0.0)))

    def appendleft(self, item, tenant: str = "default", cost: float = 1.0) -> None:
        """Front requeue (admission backpressure retry): the cost was
        already charged when the item was first popped — refund it, so the
        retry doesn't pay twice and stays immediately affordable."""
        cost = max(float(cost), 0.0)
        self._queue_for(tenant).appendleft((item, cost))
        self._deficit[tenant] = self._deficit.get(tenant, 0.0) + cost

    # ------------------------------------------------------------- dequeue

    def popleft(self):
        """Next item under WDRR order. Raises IndexError when empty (the
        deque contract)."""
        if not self:
            raise IndexError("pop from an empty WdrrQueue")
        while True:
            for tenant in list(self._queues):
                q = self._queues[tenant]
                if not q:
                    continue
                item, cost = q[0]
                if self._deficit[tenant] >= cost:
                    q.popleft()
                    if q:
                        self._deficit[tenant] -= cost
                    else:
                        # drained: no banked credit survives idleness
                        self._deficit[tenant] = 0.0
                    return item
            # nobody could afford their head: top every non-empty tenant
            # up by quantum*weight — guarantees progress (quantum > 0)
            for tenant, q in self._queues.items():
                if q:
                    self._deficit[tenant] += self.quantum * self.weight(tenant)

    def remove(self, item) -> bool:
        """Remove one queued item by identity (a drain pulls un-admitted
        requests out of the submit queue to forward them whole). Deficit
        is untouched — append never charged any."""
        for q in self._queues.values():
            for entry in q:
                if entry[0] is item:
                    q.remove(entry)
                    return True
        return False

    def refund(self, tenant: str, cost: float) -> None:
        """Return deficit charged for a popped item that never ran (a
        timed-out admission waiter, a cancelled request): without this,
        timeouts concentrated on one tenant push its realized share below
        its weight. Credited only while the tenant still has queued work —
        an idle tenant banking credit would violate the reset-on-drain
        rule."""
        q = self._queues.get(tenant)
        if q:
            self._deficit[tenant] = (
                self._deficit.get(tenant, 0.0) + max(float(cost), 0.0)
            )

    # ------------------------------------------------------------- protocol

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def __bool__(self) -> bool:
        return any(self._queues.values())

    def __iter__(self):
        for q in self._queues.values():
            for item, _cost in q:
                yield item

    def clear(self) -> None:
        self._queues.clear()
        self._deficit.clear()

    def depth(self, tenant: str) -> int:
        q = self._queues.get(tenant)
        return len(q) if q else 0
