"""Prompt-prefix locality hints for prefix-aware placement.

CoW prefix sharing (PR 1) makes a repeated prompt prefix nearly free —
but only on the node that already holds it. To let the ROUTER exploit
that across the mesh, every node advertises a compact digest of the
prompt prefixes it recently served, and the router hashes an incoming
prompt's leading blocks and prefers the peer whose advertised digest
matches.

Hashing is over the prompt TEXT in fixed-size character blocks, not over
token ids: the gateway routing a request has no tokenizer (the target
node's service owns tokenization), and text-prefix equality implies
token-prefix equality for any deterministic tokenizer fed the identical
leading string. Hashes are CHAINED — block i's hash covers blocks
0..i — so a single set-membership test per depth answers "does this peer
hold at least the first i+1 blocks of this prompt", and matching depth is
monotone by construction.

The advertised set is bounded (the digest is a wire payload repeated on
the ping cadence): an LRU of recent chains, trimmed to the newest few
dozen hashes. False positives are only a mild mis-weighting — routing is
a preference, never a correctness contract.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict

# block geometry: 256 chars ≈ 64-90 tokens for typical BPE English — a
# couple of KV blocks' worth, deep enough that a match predicts a real
# prefill saving. 4 blocks bound the hash work per request at ~1 KiB.
PREFIX_BLOCK_CHARS = 256
MAX_PREFIX_BLOCKS = 4


def prompt_prefix_hashes(prompt: str | None,
                         block_chars: int = PREFIX_BLOCK_CHARS,
                         max_blocks: int = MAX_PREFIX_BLOCKS) -> list[str]:
    """Chained hashes of the prompt's leading FULL blocks (shorter prompts
    produce fewer entries; below one block, none — there is nothing worth
    routing on). hashes[i] covers prompt[: (i+1) * block_chars]."""
    if not prompt or not isinstance(prompt, str):
        return []
    n = min(len(prompt) // block_chars, max_blocks)
    out: list[str] = []
    h = hashlib.sha256()
    for i in range(n):
        h.update(prompt[i * block_chars:(i + 1) * block_chars].encode("utf-8"))
        out.append(h.hexdigest()[:16])
    return out


class PrefixTracker:
    """Bounded LRU of prefix-chain hashes this node recently served.

    ``note()`` sits on the node's single serving funnel
    (meshnet/node._execute_local), so the advertisement tracks what the
    engine's prefix cache plausibly holds without coupling to any one
    backend. All access happens on the node's event loop — no locking."""

    def __init__(self, capacity: int = 256, advertise: int = 64):
        self.capacity = capacity
        self.advertise = advertise
        self._hashes: OrderedDict[str, bool] = OrderedDict()

    def note(self, prompt: str | None) -> None:
        for h in prompt_prefix_hashes(prompt):
            self._hashes.pop(h, None)  # LRU touch
            self._hashes[h] = True
        while len(self._hashes) > self.capacity:
            self._hashes.pop(next(iter(self._hashes)))

    def advertised(self) -> list[str]:
        """Newest-first hash list for the telemetry digest (bounded)."""
        return list(self._hashes)[-self.advertise:][::-1]

    def __len__(self) -> int:
        return len(self._hashes)


def match_depth(prompt_hashes: list[str], advertised) -> int:
    """Deepest block count the advertised set covers: chaining makes depth
    monotone, so the deepest matching hash alone tells the story."""
    if not prompt_hashes or not advertised:
        return 0
    adv = set(advertised)
    for i in range(len(prompt_hashes) - 1, -1, -1):
        if prompt_hashes[i] in adv:
            return i + 1
    return 0
