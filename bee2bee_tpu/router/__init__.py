"""SLO-aware front door: routing policy, admission control, tenant fairness.

The serving-side consumer of the mesh health plane (health.py): routes on
gossiped telemetry digests instead of the reference's static cheapest/
lowest-latency sort, sheds load with typed 429/503 + Retry-After before a
node melts, and enforces per-tenant weighted fairness from the API key
down to the engine scheduler's queue. See docs/SERVING.md.
"""

from .admission import (
    AdmissionConfig,
    AdmissionController,
    AdmissionReject,
    AdmissionTicket,
    load_admission_config,
    paged_pool_free_fraction,
    pool_exhaust_eta,
)
from .fairness import WdrrQueue
from .policy import (
    RouterPolicy,
    RouterWeights,
    load_router_weights,
    static_sort,
)
from .prefixmap import PrefixTracker, match_depth, prompt_prefix_hashes
from .tenants import (
    DEFAULT_TENANT,
    TenantRegistry,
    TenantSpec,
    load_tenant_config,
    parse_tenant_config,
)

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "AdmissionReject",
    "AdmissionTicket",
    "DEFAULT_TENANT",
    "PrefixTracker",
    "RouterPolicy",
    "RouterWeights",
    "TenantRegistry",
    "TenantSpec",
    "WdrrQueue",
    "load_admission_config",
    "load_router_weights",
    "load_tenant_config",
    "match_depth",
    "paged_pool_free_fraction",
    "pool_exhaust_eta",
    "parse_tenant_config",
    "prompt_prefix_hashes",
    "static_sort",
]
