"""In-mesh pipeline parallelism: GPipe microbatching over a `pipe` axis.

The reference's pipeline mechanism is embryonic — layer-range partial models
with hidden states forwarded between worker processes as JSON floats over
WebSocket (reference hf.py:180-205, node.py:236-277). The TPU-native
realization keeps that capability for cross-peer splits (models/stages.py)
and adds this: when the pipeline stages are chips of ONE slice, activations
move over ICI via `lax.ppermute` inside a single compiled program, not over
the network.

Mechanics (`shard_map` over a Mesh that includes a `pipe` axis):
- layer-stacked params [L, ...] reshape to [S, L/S, ...]; the S dim is
  sharded on `pipe`, so each device holds its stage's layers only
- the batch splits into M microbatches; for M + S - 1 ticks every stage
  applies its layers to its current microbatch and ppermutes the result to
  the next stage (stage 0 ingests microbatch t, the last stage's outputs
  accumulate)
- embedding and LM head run outside the shard_map (replicated params),
  so the pipelined region is exactly the layer trunk

Everything is differentiable: the pp train step is jax.grad through the
shard_map. The `data` axis composes freely (microbatches carry a data-
sharded batch dim).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..compat import shard_map

from ..models import core
from ..models.config import ModelConfig

PIPE_AXIS = "pipe"


def split_pp_params(params, n_stages: int, mesh: Mesh | None = None):
    """(head_params, staged_layers): the trunk leaves the param dict and
    comes back stage-stacked (sharded on `pipe` when a mesh is given)."""
    head = {k: v for k, v in params.items() if k != "layers"}
    staged = stage_stack_params(params, n_stages)
    if mesh is not None:
        staged = shard_stage_params(staged, mesh)
    return head, staged


def stage_stack_params(params, n_stages: int):
    """Reshape every layer-stacked leaf [L, ...] → [S, L/S, ...]."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % n_stages:
            raise ValueError(f"n_layers={L} not divisible by {n_stages} stages")
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])

    return jax.tree.map(reshape, params["layers"])


def shard_stage_params(staged, mesh: Mesh):
    """Place stage-stacked layer params with the S dim on `pipe`."""

    def put(leaf):
        spec = P(PIPE_AXIS, *([None] * (leaf.ndim - 1)))
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(put, staged)


def _apply_stage(stage_params, cfg: ModelConfig, x, positions, mask):
    """Run this device's L/S layers (scan over the local stack)."""

    def body(h, lp):
        return core.transformer_block(lp, cfg, h, positions, mask), None

    out, _ = lax.scan(body, x, stage_params)
    return out


def pipeline_apply(staged_params, cfg: ModelConfig, mesh: Mesh, x_mbs):
    """Pipelined layer trunk. x_mbs: [M, B, T, D] microbatched hidden states
    (replicated over `pipe`, batch dim shardable on `data`). Returns the
    trunk output with the same shape.
    """
    S = mesh.shape[PIPE_AXIS]
    M = x_mbs.shape[0]
    T = x_mbs.shape[2]
    if cfg.sliding_window and T > cfg.sliding_window:
        raise ValueError(
            f"pipeline trunk builds plain-causal masks; sliding_window="
            f"{cfg.sliding_window} binds at T={T} — train at <= window "
            "length or use the dense trainer"
        )
    if cfg.local_rope_theta is not None:
        # the trunk calls transformer_block without the per-layer rope
        # flag — gemma-3's sliding layers would silently rotate with the
        # GLOBAL theta/scaling
        raise ValueError(
            "pipeline trunk does not implement per-layer dual rope "
            f"(local_rope_theta, {cfg.name!r}); use the dense trainer"
        )

    in_specs = (
        jax.tree.map(lambda _: P(PIPE_AXIS), staged_params),
        P(None, "data", None, None),
    )
    out_specs = P(None, "data", None, None)

    @partial(shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    def run(stage_params, x_local):
        # stage_params leaves arrive as [1, L/S, ...] on this pipe shard
        stage_params_sq = jax.tree.map(lambda a: a[0], stage_params)
        s = lax.axis_index(PIPE_AXIS)
        B_loc = x_local.shape[1]
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B_loc, T))
        mask = jnp.tril(jnp.ones((T, T), bool))[None, None, :, :]

        state = jnp.zeros_like(x_local[0])
        out_acc = jnp.zeros_like(x_local)

        def tick(carry, t):
            state, out_acc = carry
            inp = lax.dynamic_index_in_dim(
                x_local, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            cur = jnp.where(s == 0, inp, state)
            y = _apply_stage(stage_params_sq, cfg, cur, positions, mask)
            # the last stage finished microbatch t-(S-1) this tick
            widx = jnp.clip(t - (S - 1), 0, M - 1)
            valid = (s == S - 1) & (t >= S - 1)
            prev_row = lax.dynamic_index_in_dim(out_acc, widx, 0, keepdims=False)
            out_acc = lax.dynamic_update_index_in_dim(
                out_acc, jnp.where(valid, y, prev_row), widx, 0
            )
            nxt = lax.ppermute(y, PIPE_AXIS, [(i, (i + 1) % S) for i in range(S)])
            return (nxt, out_acc), None

        (_, out_acc), _ = lax.scan(tick, (state, out_acc), jnp.arange(M + S - 1))
        # only the last stage holds real outputs; broadcast to every stage so
        # the (replicated) LM head can run anywhere
        out = lax.psum(jnp.where(s == S - 1, out_acc, jnp.zeros_like(out_acc)), PIPE_AXIS)
        return out

    return run(staged_params, x_mbs)


def pipeline_forward(params, staged_params, cfg: ModelConfig, mesh: Mesh, input_ids, n_microbatches: int):
    """Full forward with the trunk pipelined. input_ids [B, T] (B divisible
    by n_microbatches). Returns logits [B, T, V]."""
    B, T = input_ids.shape
    M = n_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    x = core.embed_tokens(params, cfg, input_ids, positions)
    x_mbs = x.reshape(M, B // M, T, -1)
    out = pipeline_apply(staged_params, cfg, mesh, x_mbs)
    return core.final_logits(params, cfg, out.reshape(B, T, -1))


def make_pp_loss(cfg: ModelConfig, mesh: Mesh, n_microbatches: int):
    """(params_no_layers, staged_layers, batch) -> scalar CE loss."""

    def loss(params, staged, batch):
        ids = batch["input_ids"]
        logits = pipeline_forward(params, staged, cfg, mesh, ids, n_microbatches)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, ids[:, 1:][..., None], axis=-1)[..., 0]
        return nll.mean()

    return loss


def make_pp_train_step(cfg: ModelConfig, mesh: Mesh, n_microbatches: int, lr: float = 1e-3):
    """Jitted SGD step through the pipelined forward: proof that the whole
    pp program (ppermute schedule included) differentiates and updates."""
    loss_fn = make_pp_loss(cfg, mesh, n_microbatches)

    @jax.jit
    def step(params, staged, batch):
        (l, grads) = jax.value_and_grad(loss_fn, argnums=(0, 1))(params, staged, batch)
        gp, gs = grads
        params = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype), params, gp)
        staged = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype), staged, gs)
        return params, staged, l

    return step
