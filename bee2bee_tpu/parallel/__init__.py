"""Parallelism layer: device meshes, sharding rules, and cross-peer parallel
serving (TP/PP/EP/SP). The reference has no analogue — its only parallelism
is layer-range pipeline hops over WebSocket (reference node.py:236-277); here
parallelism is jax.sharding over a Mesh with XLA-inserted collectives."""

from .mesh import MeshSpec, build_mesh, local_mesh  # noqa: F401
