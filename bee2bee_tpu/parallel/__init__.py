"""Parallelism layer: device meshes, sharding rules, and cross-peer parallel
serving (TP/PP/EP/SP). The reference has no analogue — its only parallelism
is layer-range pipeline hops over WebSocket (reference node.py:236-277); here
parallelism is jax.sharding over a Mesh with XLA-inserted collectives."""

from .mesh import MeshSpec, build_mesh, local_mesh  # noqa: F401


def __getattr__(name):
    # ring/pipeline pull in the model core; keep `import bee2bee_tpu.parallel`
    # light for mesh-only users
    if name in ("ring_attention", "make_sp_forward", "make_sp_train_step"):
        from . import ring

        return getattr(ring, name)
    if name in ("pipeline_forward", "make_pp_train_step", "split_pp_params"):
        from . import pipeline

        return getattr(pipeline, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
