"""Sequence-parallel SERVING: long-context attention over a seq-sharded
KV cache.

parallel/ring.py gives training its ring attention; this module gives the
*serving* engine the same first-class long-context story (the reference
has nothing here — SURVEY §5 "Long-context: absent"). Design:

- The paged KV pool [L, Hkv, NB, BS, hd] is sharded over the `seq` mesh
  axis on its SLOT dim BS (models/partition.paged_cache_spec with
  seq_sharded=True — the engine sets it iff attention='sp'), so
  per-device pool HBM is 1/n — max context scales linearly with
  devices. The block gather stays local (it indexes only the block
  dim); XLA reshards the gathered [B, S, Hkv, hd] view into this
  shard_map's contiguous S/n layout, the collective sp attention pays
  anyway.
- Attention runs as a shard_map: every device scores the (replicated)
  queries against ITS S/n view shard with an online-softmax partial
  (o_unnormalized, m, l), then one pmax + two psums over `seq` combine
  the partials exactly — the all-to-all-free flash-style merge. Score
  memory per device is [T, S/n]: the quadratic prefill term is divided
  by the axis size too.
- Everything else (projections, MLP, sampling) stays in the engine's
  single jit program; XLA's partitioner handles the seq-sharded block
  scatter writes. The continuous-batching scheduler composes unchanged
  — its allocator/table ops never touch the slot dim.

Composes with TP (`model` axis shards heads, same rules as ops/flash:
GQA needs n_kv_heads % tp == 0, MQA replicates KV) and with DP on batch.

Engine flag: EngineConfig(attention="sp") on a mesh with seq > 1.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from ..compat import shard_map

NEG_INF = -1e30


def _partial_attention(q, k, v, mask, axis_name: str):
    """Local online-softmax partial + exact cross-shard merge.

    q [B, T, H_loc, hd] (replicated over `seq`); k/v [B, S_loc, Hkv_loc, hd]
    (this device's cache shard); mask [B, 1, T, S_loc]. Returns
    [B, T, H_loc*hd] replicated over `seq`.
    """
    B, T, H, hd = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    qg = q.reshape(B, T, Hkv, G, hd).astype(jnp.float32)
    logits = jnp.einsum("btkgh,bskh->bkgts", qg, k.astype(jnp.float32))
    logits = logits / math.sqrt(hd)
    mb = mask[:, :, None, :, :]  # [B,1,1,T,S_loc] broadcast over (Hkv, G)
    logits = jnp.where(mb, logits, NEG_INF)
    m_loc = logits.max(axis=-1)  # [B, Hkv, G, T]
    p = jnp.exp(logits - m_loc[..., None])
    # a fully-masked local row is all NEG_INF: exp(0)=1 per entry — re-mask
    p = jnp.where(mb, p, 0.0)
    l_loc = p.sum(axis=-1)  # [B, Hkv, G, T]
    o_un = jnp.einsum("bkgts,bskh->btkgh", p, v.astype(jnp.float32))

    m = lax.pmax(m_loc, axis_name)
    corr = jnp.exp(m_loc - m)  # [B, Hkv, G, T]
    l = lax.psum(l_loc * corr, axis_name)
    o = lax.psum(o_un * corr.transpose(0, 3, 1, 2)[..., None], axis_name)
    out = o / jnp.where(l == 0.0, 1.0, l).transpose(0, 3, 1, 2)[..., None]
    return out.reshape(B, T, H * hd).astype(q.dtype)


def make_sp_attn_fn(mesh):
    """Build an attn_fn (core.transformer_block ABI) running seq-sharded
    cache attention. Batch rides `data` when divisible; heads ride `model`
    under TP (KV too when n_kv_heads divides, else MQA replication —
    exactly the ops/flash layout rules)."""

    def attn(q, k, v, mask, cfg, positions=None):
        B, _, H, _ = q.shape
        Hkv = k.shape[2]
        tp = mesh.shape.get("model", 1)
        data = mesh.shape.get("data", 1)
        b_ax = "data" if data > 1 and B % data == 0 else None
        h_ax = "model" if tp > 1 else None
        kv_ax = "model" if tp > 1 and Hkv % tp == 0 else None

        mapped = shard_map(
            lambda q_, k_, v_, m_: _partial_attention(q_, k_, v_, m_, "seq"),
            mesh=mesh,
            in_specs=(
                P(b_ax, None, h_ax, None),
                P(b_ax, "seq", kv_ax, None),
                P(b_ax, "seq", kv_ax, None),
                P(b_ax, None, None, "seq"),
            ),
            out_specs=P(b_ax, None, h_ax),
            check_vma=False,
        )
        return mapped(q, k, v, mask)

    return attn


def validate_sp_mesh(cfg, engine_cfg, mesh) -> None:
    """Fail fast when attention='sp' cannot run on this mesh/model."""
    sp = mesh.shape.get("seq", 1)
    if sp <= 1:
        raise ValueError(
            "attention='sp' needs a mesh with seq > 1 (got "
            f"{dict(mesh.shape)}); use attention='dense'/'flash' otherwise"
        )
    S = min(engine_cfg.max_seq_len, cfg.max_seq_len)
    if S % sp:
        raise ValueError(
            f"attention='sp' needs max_seq_len={S} divisible by the seq "
            f"axis {sp} (the cache capacity dim is sharded over it)"
        )
    bs = getattr(engine_cfg, "kv_block_size", 0) or 0
    if bs % sp:
        # the pool's SLOT dim carries the seq sharding and the gathered
        # view's width is table_width * kv_block_size: a block size the
        # axis doesn't divide would silently drop the 1/seq pool sharding
        # (engine._fit_spec falls back to replicated) AND crash the first
        # decode when shard_map can't split the narrow gathered view
        raise ValueError(
            f"attention='sp' needs kv_block_size={bs} divisible by the "
            f"seq axis {sp} (the pool's slot dim is sharded over it and "
            "every gathered-view width is a multiple of the block size)"
        )
    tp = mesh.shape.get("model", 1)
    if tp > 1:
        if cfg.n_heads % tp:
            raise ValueError(
                f"attention='sp' with TP needs n_heads={cfg.n_heads} "
                f"divisible by model axis {tp}"
            )
        if cfg.n_kv_heads % tp and cfg.n_kv_heads != 1:
            raise ValueError(
                f"attention='sp' cannot run GQA with n_kv_heads="
                f"{cfg.n_kv_heads} replicated across model axis {tp} "
                "(local kv-head mapping would be wrong); MQA (n_kv_heads=1) "
                "or divisible GQA only"
            )
