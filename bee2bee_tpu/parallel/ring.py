"""Ring attention: sequence/context parallelism over the `seq` mesh axis.

The reference has NOTHING here (SURVEY §2.4: SP/CP/ring "No — nothing
anywhere; sequence length is bounded by single-host HF generate"). For a
TPU framework long context is first-class, so this module provides:

- `ring_attention_local`: blockwise-causal attention with an online
  (flash-style) softmax whose K/V blocks rotate around the `seq` axis via
  `jax.lax.ppermute` — each device only ever holds O(T/n) keys, so max
  context scales linearly with the number of devices, and the permute
  rides ICI concurrently with compute.
- `ring_attention`: the shard_map wrapper over a Mesh for direct use.
- `make_sp_forward` / `make_sp_train_step`: a full causal-LM forward /
  train step sharded ('data','seq') where every attention is a ring —
  the DP×SP training path (TP composes via the dense-path trainer
  instead; the SP mesh must have model=expert=1).

Numerics: logits/softmax accumulate in f32 with the standard running
(max, sum, out) update; a fully-masked block contributes exp(-1e30-m)=0
rather than NaN.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import core
from ..compat import shard_map
from ..models.config import ModelConfig

NEG_INF = -1e30


def _block_attend(q, k, v, mask, acc):
    """One online-softmax update. q [B,Tq,Hkv,G,hd]; k/v [B,Tk,Hkv,hd];
    mask [Tq,Tk] bool; acc = (o [B,Tq,Hkv,G,hd] f32, m, l [B,Hkv,G,Tq] f32)."""
    o, m, l = acc
    hd = q.shape[-1]
    logits = jnp.einsum(
        "btkgh,bskh->bkgts", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(hd)
    logits = jnp.where(mask[None, None, None, :, :], logits, NEG_INF)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    scale = jnp.exp(m - m_new)
    l = l * scale + p.sum(axis=-1)
    pv = jnp.einsum("bkgts,bskh->btkgh", p, v.astype(jnp.float32))
    o = o * scale.transpose(0, 3, 1, 2)[..., None] + pv
    return o, m_new, l


def ring_attention_local(q, k, v, axis_name: str, axis_size: int):
    """Causal ring attention on per-device shards (call inside shard_map).

    q [B, Tl, H, hd]; k, v [B, Tl, Hkv, hd] — Tl is the LOCAL chunk of a
    global sequence laid out contiguously along `axis_name` (device i owns
    positions [i*Tl, (i+1)*Tl)). Returns [B, Tl, H*hd].
    """
    B, Tl, H, hd = q.shape
    Hkv = k.shape[2]
    G = H // Hkv
    idx = lax.axis_index(axis_name)

    qg = q.reshape(B, Tl, Hkv, G, hd)
    o = jnp.zeros((B, Tl, Hkv, G, hd), jnp.float32)
    m = jnp.full((B, Hkv, G, Tl), NEG_INF, jnp.float32)
    l = jnp.zeros((B, Hkv, G, Tl), jnp.float32)

    t = jnp.arange(Tl, dtype=jnp.int32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    k_cur, v_cur = k, v
    for step in range(axis_size):
        # after `step` rotations device idx holds the block that originated
        # on device (idx - step) mod n
        src = (idx - step) % axis_size
        qpos = idx * Tl + t  # global positions of local queries
        kpos = src * Tl + t
        mask = kpos[None, :] <= qpos[:, None]  # [Tl, Tl] causal
        o, m, l = _block_attend(qg, k_cur, v_cur, mask, (o, m, l))
        if step != axis_size - 1:  # skip the final (unused) rotation
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    # l > 0 always: the self block's diagonal is never masked
    out = o / l.transpose(0, 3, 1, 2)[..., None]
    return out.reshape(B, Tl, H * hd).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "seq"):
    """shard_map wrapper: q [B,T,H,hd], k/v [B,T,Hkv,hd] with T divisible
    by mesh.shape[axis_name]; batch rides 'data' when present."""
    n = mesh.shape[axis_name]
    batch_axis = (
        "data"
        if mesh.shape.get("data", 1) > 1 and q.shape[0] % mesh.shape["data"] == 0
        else None
    )
    spec = P(batch_axis, axis_name, None, None)

    mapped = shard_map(
        partial(ring_attention_local, axis_name=axis_name, axis_size=n),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=P(batch_axis, axis_name, None),
        check_vma=False,
    )
    return mapped(q, k, v)


# ------------------------------------------------- sequence-parallel model


def make_sp_forward(cfg: ModelConfig, mesh: Mesh, remat: bool = False):
    """Full-model forward with every attention as a ring over `seq`.

    Requires model/expert axes of size 1 (TP/EP compose via the pjit path
    instead — mixing manual shard_map TP collectives into this would
    duplicate what XLA already does well there).

    Returns fn(params, input_ids [B,T]) -> logits [B,T,V]; params must be
    replicated across data/seq (they are: partition_specs only uses
    model/expert axes, which are singleton here).
    """
    for ax in ("model", "expert"):
        if mesh.shape.get(ax, 1) != 1:
            raise ValueError(
                f"make_sp_forward needs {ax}=1 in the mesh (got {mesh.shape})"
            )
    n_seq = mesh.shape["seq"]
    attn = partial(ring_attention_local, axis_name="seq", axis_size=n_seq)

    def attn_fn(q, k, v, mask, _cfg, positions=None):
        return attn(q, k, v)

    def local_fn(params, ids):
        # ids: the LOCAL [B_loc, T_loc] chunk
        B, Tl = ids.shape
        start = lax.axis_index("seq") * Tl
        positions = jnp.broadcast_to(
            start + jnp.arange(Tl, dtype=jnp.int32), (B, Tl)
        )
        x = core.embed_tokens(params, cfg, ids, positions)

        def layer(x, lp):
            return (
                core.transformer_block(
                    lp, cfg, x, positions, mask=None, attn_fn=attn_fn
                ),
                None,
            )

        # long context is exactly where activation memory peaks — honor the
        # trainer's remat flag like core.forward does (prevent_cse=False:
        # scan's loop structure already blocks CSE)
        body = jax.checkpoint(layer, prevent_cse=False) if remat else layer
        x, _ = lax.scan(body, x, params["layers"])
        return core.final_logits(params, cfg, x)

    param_specs = jax.tree.map(lambda _: P(), jax.eval_shape(
        lambda: core.init_params(cfg, jax.random.key(0))
    ))

    mapped = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(param_specs, P("data", "seq")),
        out_specs=P("data", "seq", None),
        check_vma=False,
    )

    def sp_forward(params, ids):
        # guard at the PUBLIC surface (shape is static here): ring
        # attention builds plain-causal block masks, so a windowed model
        # past its window would silently attend beyond it and diverge
        # from core.forward inference
        if cfg.sliding_window and ids.shape[1] > cfg.sliding_window:
            raise ValueError(
                f"ring-SP does not implement sliding_window="
                f"{cfg.sliding_window} (seq len {ids.shape[1]} exceeds it); "
                "train/score at <= window length or use the dense path"
            )
        if cfg.local_rope_theta is not None:
            # the ring trunk calls transformer_block without the per-layer
            # rope flag — sliding layers would rotate with the global theta
            raise ValueError(
                "ring-SP does not implement per-layer dual rope "
                f"(local_rope_theta, {cfg.name!r}); use the dense path"
            )
        return mapped(params, ids)

    return sp_forward


def make_sp_train_step(cfg: ModelConfig, tcfg, mesh: Mesh, donate: bool = True):
    """DP×SP train step: ring attention inside, psum-mean loss/grads.

    Mirrors trainer.make_train_step's contract: (state, batch) ->
    (state, metrics) — same loss/step machinery (trainer.xent_loss_metrics
    / make_step_from_loss), only the forward differs.
    """
    from ..train.trainer import make_step_from_loss, xent_loss_metrics

    sp_forward = make_sp_forward(cfg, mesh, remat=tcfg.remat)

    def loss(params, batch):
        ids = batch["input_ids"]
        logits = sp_forward(params, ids)
        return xent_loss_metrics(logits, ids, batch.get("loss_mask"))

    return make_step_from_loss(
        loss, tcfg, NamedSharding(mesh, P("data", "seq")), donate=donate
    )
