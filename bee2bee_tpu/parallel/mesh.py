"""Device mesh construction: the single place axis names are defined.

Axes (SURVEY §7 step 2: "mesh axes declared once so single-chip is the
degenerate 1x1 mesh"):

- ``data``   — batch/data parallel replicas
- ``model``  — tensor-parallel shards (attention heads / MLP columns)
- ``expert`` — MoE expert-parallel shards
- ``seq``    — sequence/context parallel (ring attention)

Every axis defaults to 1, so any program written against these names runs
unchanged from one chip to a v5e-16 slice — only the mesh shape changes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "seq", "expert", "model")


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape over the named axes."""

    data: int = 1
    seq: int = 1
    expert: int = 1
    model: int = 1
    # axis order in the physical device grid; innermost (last) axis gets
    # devices that are closest in ICI topology, so keep `model` last: TP
    # collectives are the most latency-sensitive.
    order: tuple[str, ...] = field(default=AXES)

    @property
    def shape(self) -> dict[str, int]:
        return {"data": self.data, "seq": self.seq, "expert": self.expert, "model": self.model}

    @property
    def size(self) -> int:
        return self.data * self.seq * self.expert * self.model

    @classmethod
    def from_dict(cls, d: dict[str, int]) -> "MeshSpec":
        unknown = set(d) - set(AXES)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXES}")
        return cls(**{k: int(v) for k, v in d.items()})


def build_mesh(spec: MeshSpec | dict | None = None, devices=None) -> Mesh:
    """Build a Mesh from a spec. With no spec, all local devices go on the
    `model` axis (the right default for single-host TP serving)."""
    if isinstance(spec, dict):
        spec = MeshSpec.from_dict(spec)
    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        spec = MeshSpec(model=len(devices))
    if spec.size > len(devices):
        raise ValueError(f"mesh needs {spec.size} devices, have {len(devices)}")
    devices = devices[: spec.size]
    dims = [spec.shape[a] for a in spec.order]
    grid = np.array(devices, dtype=object).reshape(dims)
    return Mesh(grid, spec.order)


def local_mesh() -> Mesh:
    """Degenerate all-axes-1 mesh on the first local device."""
    return build_mesh(MeshSpec(), devices=jax.devices()[:1])


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)
