"""Multi-host SPMD: one logical mesh spanning processes/hosts.

The reference scales across machines with per-layer JSON-over-WebSocket
hops (reference node.py:94-182) — bandwidth-bound and lock-step slow.
The TPU-native equivalent is jax.distributed: every host runs the SAME
jit program over a GLOBAL mesh; XLA inserts collectives that ride
ICI within a slice and DCN between hosts. This module is the thin,
testable entry to that:

- ``init_multihost``: wraps jax.distributed.initialize with the node
  config's coordinator knobs and returns the global device list.
- ``global_mesh``: builds a MeshSpec-shaped Mesh over ALL processes'
  devices (jax.devices() is global after initialize).
- ``global_array``: every host holds the SAME global batch (same corpus
  + shuffle seed) and each materializes exactly its addressable shards
  via ``make_array_from_callback`` — correct for ANY sharding, including
  meshes whose data axis does not span processes (where a naive
  per-process row split would silently feed different data per host).
- ``host_local_batch``: convenience row-slice for loaders that shard
  reading; only valid when the batch rows genuinely map to processes.

Tested for real in tests/test_multihost.py: two localhost processes,
each with 4 virtual CPU devices, form one 8-device mesh and take a
dp2 x sp2 x tp2 train step whose loss matches the single-process
8-device run bit-for-bit.
"""

from __future__ import annotations

import jax
import numpy as np

from .mesh import MeshSpec, build_mesh


def init_multihost(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_device_count: int | None = None,
) -> list:
    """Join the jax.distributed cluster; returns the GLOBAL device list.

    coordinator: "host:port" of process 0 (any free port). Call before
    any other jax API touches the backend. Idempotent re-init raises in
    jax — callers own process lifecycle.
    """
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=(
            list(range(local_device_count)) if local_device_count else None
        ),
    )
    return jax.devices()


def global_mesh(spec: MeshSpec | dict | None = None):
    """A Mesh over every process's devices (call after init_multihost)."""
    return build_mesh(spec, devices=jax.devices())


def process_mesh_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def host_local_batch(global_batch: np.ndarray) -> np.ndarray:
    """This process's row-slice of a batch sharded over hosts (batch dim
    must divide process_count)."""
    n = jax.process_count()
    b = global_batch.shape[0]
    if b % n:
        raise ValueError(f"global batch {b} not divisible by {n} processes")
    i = jax.process_index()
    per = b // n
    return global_batch[i * per : (i + 1) * per]


def global_array(global_batch: np.ndarray, mesh, spec):
    """Assemble one global sharded array from the FULL global batch
    (identical on every host): each process materializes exactly its
    addressable shards. Works for any sharding — data axis spanning
    processes, replicated batches under pure TP, anything between."""
    from jax.sharding import NamedSharding

    return jax.make_array_from_callback(
        global_batch.shape,
        NamedSharding(mesh, spec),
        lambda idx: global_batch[idx],
    )
