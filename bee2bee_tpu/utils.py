"""Infra utilities: home dir, atomic JSON persistence, ids, hashing, metrics.

Capability parity with reference utils (/root/reference/bee2bee/utils.py:11-135)
with one deliberate divergence: `get_system_metrics` never fabricates numbers.
The reference simulates throughput as `cpu_percent * 0.85` and invents a
trust_score (utils.py:129-132); here throughput is a real measured
tokens/sec figure reported by the serving engine (see MetricsAggregator),
and accelerator telemetry comes from `jax.local_devices()` memory stats
instead of nvidia-smi.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import logging
import os
import socket
import tempfile
import threading
import time
import uuid
from pathlib import Path
from typing import Any


def bee2bee_home() -> Path:
    """Per-user state directory (env `BEE2BEE_TPU_HOME` overrides).

    Mirrors reference `bee2bee_home` (utils.py:11-18).
    """
    root = os.environ.get("BEE2BEE_TPU_HOME")
    home = Path(root) if root else Path.home() / ".bee2bee_tpu"
    home.mkdir(parents=True, exist_ok=True)
    return home


def data_file(name: str) -> Path:
    return bee2bee_home() / name


def save_json(path: Path | str, obj: Any) -> None:
    """Atomic JSON write: tmp file + os.replace (reference utils.py:37-40)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=2, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_json_source(source: str | None, env_var: str,
                     opener: str = "{") -> Any:
    """THE inline-JSON-or-file-path config convention (BEE2BEE_SLO_CONFIG,
    BEE2BEE_TENANTS, BEE2BEE_ADMISSION, BEE2BEE_ROUTER share it): `source`
    wins, else the env var; a value starting with `opener` parses inline,
    anything else is a path read and parsed. Returns None when no source
    is configured at all; parse/read errors raise — these configs fail
    the node at construction, never route on garbage."""
    raw = source if source is not None else os.environ.get(env_var)
    if not raw:
        return None
    text = raw.strip()
    if not text.startswith(opener):
        text = Path(text).read_text()
    return json.loads(text)


def load_json(path: Path | str, default: Any = None) -> Any:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return default


def new_id(prefix: str = "id") -> str:
    """Unique id `prefix-<12 hex>` (reference utils.py:43-44)."""
    return f"{prefix}-{uuid.uuid4().hex[:12]}"


def sha256_hex(data: bytes | str) -> str:
    if isinstance(data, str):
        data = data.encode("utf-8")
    return hashlib.sha256(data).hexdigest()


def get_lan_ip(default: str | None = "127.0.0.1") -> str | None:
    """Best-effort LAN IP via the UDP-connect trick (reference utils.py:68-80).
    Returns `default` (pass None to detect failure) when no route exists."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.settimeout(0.5)
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return default


def now_ms() -> int:
    return int(time.time() * 1000)


class MetricsAggregator:
    """Rolling real-throughput accounting for a serving node.

    Replaces the reference's simulated telemetry (utils.py:129-132) with
    measured values: every completed generation reports (new_tokens,
    latency_s) and the aggregator exposes tokens/sec over a sliding window.
    Thread-safe: services may complete requests from executor threads.
    """

    def __init__(self, window_s: float = 60.0):
        self.window_s = window_s
        self._events: list[tuple[float, int, float]] = []  # (t, tokens, latency_s)
        self._lock = threading.Lock()
        self._total_tokens = 0
        self._total_requests = 0

    def record(self, new_tokens: int, latency_s: float) -> None:
        with self._lock:
            self._events.append((time.time(), int(new_tokens), float(latency_s)))
            self._total_tokens += int(new_tokens)
            self._total_requests += 1
            self._prune()

    def _prune(self) -> None:
        cutoff = time.time() - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.pop(0)

    def snapshot(self) -> dict:
        import time as _time

        with self._lock:
            self._prune()
            toks = sum(e[1] for e in self._events)
            lats = [e[2] for e in self._events if e[2] > 0]
            # divide by actual elapsed span (capped at the window), not the
            # full window — else a fresh node underreports for window_s secs
            if self._events:
                span = max(_time.time() - self._events[0][0], self._events[0][2], 1e-3)
                span = min(span, self.window_s)
            else:
                span = 1.0
            return {
                "tokens_per_sec": round(toks / span, 3),
                "window_tokens": toks,
                "p50_latency_s": round(_percentile(lats, 0.5), 4) if lats else None,
                "total_tokens": self._total_tokens,
                "total_requests": self._total_requests,
            }


def _percentile(values: list[float], q: float) -> float:
    xs = sorted(values)
    if not xs:
        return 0.0
    idx = min(int(q * len(xs)), len(xs) - 1)
    return xs[idx]


def get_accelerator_info() -> dict:
    """Describe local accelerators via JAX (replaces nvidia-smi polling,
    reference utils.py:102-118). Safe to call without jax initialized devices;
    returns a CPU-only record on failure."""
    try:
        import jax

        devs = jax.local_devices()
        kinds: dict[str, int] = {}
        for d in devs:
            kinds[d.device_kind] = kinds.get(d.device_kind, 0) + 1
        mem = None
        try:
            stats = devs[0].memory_stats()
            if stats:
                mem = {
                    "bytes_in_use": stats.get("bytes_in_use"),
                    "bytes_limit": stats.get("bytes_limit"),
                }
        except Exception:
            pass
        return {
            "platform": devs[0].platform if devs else "cpu",
            "device_count": len(devs),
            "device_kinds": kinds,
            "memory": mem,
        }
    except Exception:
        return {"platform": "cpu", "device_count": 0, "device_kinds": {}, "memory": None}


def get_system_metrics(throughput: MetricsAggregator | None = None) -> dict:
    """System + accelerator metrics. Schema keeps the reference's keys
    (utils.py:128-133) for registry/UI compatibility, but every value is
    measured: cpu/ram via psutil, gpu via jax memory stats, throughput from
    the engine's MetricsAggregator (0.0 if none supplied — never simulated).
    """
    cpu = ram = 0.0
    try:
        import psutil

        cpu = psutil.cpu_percent(interval=None)
        ram = psutil.virtual_memory().percent
    except Exception:
        pass
    accel = get_accelerator_info()
    gpu_pct = 0.0
    if accel["memory"] and accel["memory"].get("bytes_limit"):
        gpu_pct = round(
            100.0 * (accel["memory"].get("bytes_in_use") or 0) / accel["memory"]["bytes_limit"],
            2,
        )
    tp = throughput.snapshot() if throughput else None
    return {
        "cpu": cpu,
        "ram": ram,
        "gpu": gpu_pct,
        "throughput": (tp or {}).get("tokens_per_sec", 0.0),
        "p50_latency_s": (tp or {}).get("p50_latency_s"),
        "accelerator": accel,
        "timestamp": now_ms(),
    }


async def pump_queue_until(task, q, emit):
    """Forward queued items through `emit` (awaited per item) until `task`
    completes, then drain anything queued after completion. Returns the
    task's result (re-raising its exception).

    The cancellation-sensitive streaming pump shared by the mesh node's
    GEN_CHUNK forwarding and the web gateway's HTTP chunk relay: cancelling
    a waiting `q.get()` is safe because put_nowait appends to the queue's
    internal deque, so items survive for the post-completion drain.

    When `emit` raises (consumer hung up mid-stream), the producer task is
    cancelled and its outcome consumed — the generation must not keep
    running to its token budget for nobody, and its eventual exception
    must not surface as "Task exception was never retrieved". (Work a
    producer already handed to an executor thread finishes in that thread;
    cancellation stops everything scheduled after it.)
    """
    getter = None
    try:
        while True:
            getter = asyncio.create_task(q.get())
            done, _ = await asyncio.wait(
                {getter, task}, return_when=asyncio.FIRST_COMPLETED
            )
            if getter in done:
                await emit(getter.result())
                continue
            getter.cancel()
            break
        result = await task
        while not q.empty():
            await emit(q.get_nowait())
        return result
    except BaseException:
        # also reached when the pump itself is cancelled (client hung up):
        # neither the producer nor a pending q.get() may be left dangling
        if getter is not None and not getter.done():
            getter.cancel()
        task.cancel()
        with contextlib.suppress(BaseException):
            await task
        raise


_task_logger = logging.getLogger("bee2bee_tpu.tasks")


def log_task_exception(task: asyncio.Task) -> None:
    """Done-callback that surfaces a background task's exception instead of
    letting it vanish into "Task exception was never retrieved" at GC time.
    Retrieving the exception here also marks it retrieved, so the asyncio
    destructor warning never fires."""
    if task.cancelled():
        return
    exc = task.exception()
    if exc is not None:
        _task_logger.error(
            "background task %r crashed: %r",
            task.get_name(),
            exc,
            exc_info=exc,
        )


class TaskTracker:
    """Tracked background-task spawning: the `node._spawn` pattern as a
    reusable helper, and the blessed route past meshlint ML-R002.

    A raw ``asyncio.create_task`` whose handle is dropped has two failure
    modes: its exception is silently swallowed, and asyncio holds only a
    weak reference so GC can cancel it mid-flight. The tracker keeps a
    strong reference until the task finishes, logs any exception via
    `log_task_exception`, and cancels everything still running on
    `cancel_all()` (stop/teardown). Policy (docs/ANALYSIS.md): a raw
    create_task is fine only when the handle is awaited on every path in
    the same function (e.g. `pump_queue_until`); every background task
    goes through a tracker.
    """

    def __init__(self, name: str = "tasks"):
        self.name = name
        self._tasks: set[asyncio.Task] = set()

    def spawn(self, coro, name: str | None = None) -> asyncio.Task:
        task = asyncio.get_running_loop().create_task(coro, name=name)
        self._tasks.add(task)
        task.add_done_callback(self._reap)
        return task

    def _reap(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        log_task_exception(task)

    def __len__(self) -> int:
        return len(self._tasks)

    def __iter__(self):
        return iter(list(self._tasks))

    async def cancel_all(self) -> None:
        tasks = [t for t in self._tasks if not t.done()]
        for t in tasks:
            t.cancel()
        for t in tasks:
            with contextlib.suppress(BaseException):
                await t
