"""Dataset preprocessing: tokenize + pack into static-shape batches.

Capability parity with the reference's thin wrapper (reference
datasets.py:5-22: build_preprocess_config + load_and_preprocess over HF
datasets, truncating to max_length=128 — reference hf.py:161-176), made
TPU-idiomatic: XLA wants STATIC shapes, so instead of per-example ragged
truncation this packs token streams into dense ``[batch, seq_len]``
blocks with loss masks, yielding numpy batches ready for
``jax.device_put`` onto a ('data','seq')-sharded mesh.

Sources: an in-memory list of texts (tests/offline), a local text file,
or — when the `datasets` package and a local/cached dataset are
available — an HF dataset. Nothing here touches the network unless the
caller passes an HF dataset name that isn't cached (gated the same way
the reference gates transformers, reference hf.py:7-20).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Iterator

import numpy as np


def has_datasets() -> bool:
    try:
        import datasets  # noqa: F401

        return True
    except ImportError:
        return False


@dataclass(frozen=True)
class PreprocessConfig:
    """Mirrors reference build_preprocess_config (datasets.py:5-16), with
    packing controls added."""

    text_field: str = "text"
    seq_len: int = 128
    batch_size: int = 8
    append_eos: bool = True
    drop_remainder: bool = True  # ragged tails would force a recompile
    shuffle_seed: int | None = None


def tokenize_texts(
    texts: Iterable[str], tokenizer, cfg: PreprocessConfig
) -> np.ndarray:
    """Concatenate token ids of all texts into one flat int32 stream."""
    stream: list[int] = []
    eos = getattr(tokenizer, "eos_token_id", None)
    for t in texts:
        ids = tokenizer.encode(t)
        stream.extend(int(i) for i in ids)
        if cfg.append_eos and eos is not None:
            stream.append(int(eos))
    return np.asarray(stream, dtype=np.int32)


def pack_stream_masked(
    stream: np.ndarray, cfg: PreprocessConfig
) -> tuple[np.ndarray, np.ndarray]:
    """Flat stream → ([n_blocks, seq_len] blocks, [n_blocks, seq_len] mask).

    The mask marks real stream positions with 1.0 and padded-tail zeros
    with 0.0. Padding only ever exists in the final block (and only when
    ``drop_remainder=False``); full blocks are entirely valid regardless
    of which token ids they contain — including id 0, which is a real
    vocabulary token in GPT-2-family tokenizers and must stay in the loss.
    """
    n_blocks = len(stream) // cfg.seq_len
    if n_blocks == 0:
        if not cfg.drop_remainder and len(stream):
            pad = np.zeros(cfg.seq_len, np.int32)
            pad[: len(stream)] = stream
            mask = np.zeros(cfg.seq_len, np.float32)
            mask[: len(stream)] = 1.0
            return pad[None, :], mask[None, :]
        return np.zeros((0, cfg.seq_len), np.int32), np.zeros(
            (0, cfg.seq_len), np.float32
        )
    used = stream[: n_blocks * cfg.seq_len].reshape(n_blocks, cfg.seq_len)
    masks = np.ones((n_blocks, cfg.seq_len), np.float32)
    if not cfg.drop_remainder and len(stream) > n_blocks * cfg.seq_len:
        tail = np.zeros(cfg.seq_len, np.int32)
        rest = stream[n_blocks * cfg.seq_len :]
        tail[: len(rest)] = rest
        tmask = np.zeros(cfg.seq_len, np.float32)
        tmask[: len(rest)] = 1.0
        used = np.concatenate([used, tail[None, :]], axis=0)
        masks = np.concatenate([masks, tmask[None, :]], axis=0)
    return used, masks


def pack_stream(stream: np.ndarray, cfg: PreprocessConfig) -> np.ndarray:
    """Flat stream → [n_blocks, seq_len] dense blocks (static shapes)."""
    return pack_stream_masked(stream, cfg)[0]


@dataclass
class PackedDataset:
    """Dense token blocks + batch iteration with loss masks.

    Batches are dicts {"input_ids": [B,T] int32, "loss_mask": [B,T] f32}
    — exactly what train.loss_fn consumes.
    """

    blocks: np.ndarray  # [N, T]
    batch_size: int = 8
    _rng: np.random.Generator | None = field(default=None, repr=False)
    masks: np.ndarray | None = None  # [N, T] f32; None ⇒ every position valid

    @property
    def n_batches(self) -> int:
        return len(self.blocks) // self.batch_size

    def shuffle(self, seed: int) -> "PackedDataset":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(self.blocks))
        masks = self.masks[perm] if self.masks is not None else None
        return PackedDataset(self.blocks[perm], self.batch_size, rng, masks)

    def __iter__(self) -> Iterator[dict]:
        for i in range(self.n_batches):
            sl = slice(i * self.batch_size, (i + 1) * self.batch_size)
            chunk = self.blocks[sl]
            mask = (
                self.masks[sl]
                if self.masks is not None
                else np.ones(chunk.shape, np.float32)
            )
            yield {"input_ids": chunk, "loss_mask": mask}

    def __len__(self) -> int:
        return self.n_batches

    def repeat(self) -> Iterator[dict]:
        """Infinite epoch loop, reshuffling each pass when seeded."""
        epoch = 0
        while True:
            ds = self.shuffle(epoch) if self._rng is not None else self
            yield from ds
            epoch += 1


def from_texts(
    texts: Iterable[str], tokenizer, cfg: PreprocessConfig | None = None
) -> PackedDataset:
    cfg = cfg or PreprocessConfig()
    stream = tokenize_texts(texts, tokenizer, cfg)
    blocks, masks = pack_stream_masked(stream, cfg)
    ds = PackedDataset(blocks, cfg.batch_size, masks=masks)
    if cfg.shuffle_seed is not None:
        ds = ds.shuffle(cfg.shuffle_seed)
    return ds


def from_text_file(
    path: str | Path, tokenizer, cfg: PreprocessConfig | None = None
) -> PackedDataset:
    text = Path(path).read_text()
    # blank-line-separated documents, like HF text datasets
    docs = [d for d in text.split("\n\n") if d.strip()]
    return from_texts(docs, tokenizer, cfg)


def load_and_preprocess(
    dataset_name: str,
    tokenizer,
    cfg: PreprocessConfig | None = None,
    split: str = "train",
    limit: int | None = None,
) -> PackedDataset:
    """HF-datasets path (reference load_and_preprocess, datasets.py:19-22).

    Requires the `datasets` package and a cached/local dataset (no egress
    in the build environment).
    """
    if not has_datasets():
        raise RuntimeError("the `datasets` package is not installed")
    import datasets as hfds

    cfg = cfg or PreprocessConfig()
    ds = hfds.load_dataset(dataset_name, split=split)
    texts = (ex[cfg.text_field] for ex in (ds.select(range(limit)) if limit else ds))
    return from_texts(texts, tokenizer, cfg)
