"""NAT traversal: ordered auto-port-forwarding for mesh nodes.

Capability parity with the reference's ``bee2bee/nat.py`` — the
UPnP → NAT-PMP → PCP → STUN-detection chain (reference nat.py:50-116),
hand-rolled NAT-PMP/PCP request packets (nat.py:207-320), public-IP
discovery with a TTL cache (nat.py:411-441), gateway detection
(nat.py:454-478), mapping cleanup (nat.py:563-580) — rebuilt so that
every wire codec is a pure function (offline-testable against loopback
fakes) and the network chain is data-driven.

Datacenter TPU hosts rarely sit behind consumer NAT, so the whole module
is an optional assist: every step degrades to "no mapping, here's what
we observed" without raising.
"""

from __future__ import annotations

import os
import secrets
import socket
import struct
import time
from dataclasses import dataclass, field

from .stun import STUNClient

NATPMP_PORT = 5351
PCP_PORT = 5351
NATPMP_VERSION = 0
PCP_VERSION = 2

# NAT-PMP opcodes (RFC 6886)
NATPMP_OP_PUBLIC_ADDR = 0
NATPMP_OP_MAP_UDP = 1
NATPMP_OP_MAP_TCP = 2

# PCP opcodes (RFC 6887)
PCP_OP_MAP = 1
PCP_PROTO_TCP = 6
PCP_PROTO_UDP = 17


@dataclass
class PortMapping:
    """Outcome of one forwarding attempt."""

    ok: bool
    method: str  # "upnp" | "natpmp" | "pcp" | "stun" | "none"
    internal_port: int
    external_port: int = 0
    public_ip: str | None = None
    lifetime: int = 0
    detail: str = ""
    tcp: bool = True  # protocol the mapping was created for (cleanup needs it)
    nonce: bytes | None = None  # PCP: delete must reuse the creating nonce (RFC 6887)


# ----------------------------------------------------------------- NAT-PMP


def build_natpmp_public_addr_request() -> bytes:
    return struct.pack("!BB", NATPMP_VERSION, NATPMP_OP_PUBLIC_ADDR)


def parse_natpmp_public_addr_response(data: bytes) -> str | None:
    if len(data) < 12:
        return None
    version, opcode, result = struct.unpack("!BBH", data[:4])
    if version != NATPMP_VERSION or opcode != NATPMP_OP_PUBLIC_ADDR + 128:
        return None
    if result != 0:
        return None
    return socket.inet_ntoa(data[8:12])


def build_natpmp_map_request(
    internal_port: int, external_port: int, lifetime: int = 3600, tcp: bool = True
) -> bytes:
    opcode = NATPMP_OP_MAP_TCP if tcp else NATPMP_OP_MAP_UDP
    return struct.pack(
        "!BBHHHI", NATPMP_VERSION, opcode, 0, internal_port, external_port, lifetime
    )


def parse_natpmp_map_response(data: bytes) -> tuple[int, int, int] | None:
    """Return (internal_port, external_port, lifetime) on success."""
    if len(data) < 16:
        return None
    version, opcode, result = struct.unpack("!BBH", data[:4])
    if version != NATPMP_VERSION or opcode not in (
        NATPMP_OP_MAP_UDP + 128,
        NATPMP_OP_MAP_TCP + 128,
    ):
        return None
    if result != 0:
        return None
    internal, external, lifetime = struct.unpack("!HHI", data[8:16])
    return internal, external, lifetime


# --------------------------------------------------------------------- PCP


def _ipv4_mapped(ip: str) -> bytes:
    return b"\x00" * 10 + b"\xff\xff" + socket.inet_aton(ip)


def build_pcp_map_request(
    client_ip: str,
    internal_port: int,
    external_port: int,
    lifetime: int = 3600,
    tcp: bool = True,
    nonce: bytes | None = None,
) -> tuple[bytes, bytes]:
    """PCP v2 MAP request (24-byte header + 36-byte MAP payload)."""
    nonce = nonce or secrets.token_bytes(12)
    if len(nonce) != 12:
        raise ValueError("nonce must be 12 bytes")
    header = (
        struct.pack("!BBHI", PCP_VERSION, PCP_OP_MAP, 0, lifetime)
        + _ipv4_mapped(client_ip)
    )
    payload = (
        nonce
        + struct.pack("!B3xHH", PCP_PROTO_TCP if tcp else PCP_PROTO_UDP,
                      internal_port, external_port)
        + _ipv4_mapped("0.0.0.0")  # suggested external address: any
    )
    return header + payload, nonce


def parse_pcp_map_response(data: bytes, nonce: bytes) -> tuple[int, int, str] | None:
    """Return (external_port, lifetime, external_ip) on success."""
    if len(data) < 60:
        return None
    version, op_r, _, result = struct.unpack("!BBBB", data[:4])
    if version != PCP_VERSION or op_r != (PCP_OP_MAP | 0x80) or result != 0:
        return None
    lifetime = struct.unpack("!I", data[4:8])[0]
    body = data[24:]
    if body[:12] != nonce:
        return None
    external_port = struct.unpack("!H", body[18:20])[0]
    external_ip = socket.inet_ntoa(body[20 + 12 : 20 + 16])
    return external_port, lifetime, external_ip


# -------------------------------------------------------------- discovery


def get_gateway_ip() -> str | None:
    """Default-route gateway, via /proc/net/route (Linux) or a .1 guess."""
    try:
        with open("/proc/net/route") as fh:
            for line in fh.readlines()[1:]:
                parts = line.split()
                if len(parts) >= 3 and parts[1] == "00000000":
                    return socket.inet_ntoa(struct.pack("<I", int(parts[2], 16)))
    except (OSError, ValueError):
        pass
    lan = get_lan_ip()
    if lan:
        return ".".join(lan.split(".")[:3] + ["1"])
    return None


def get_lan_ip() -> str | None:
    """None when no route exists (delegates to utils.get_lan_ip)."""
    from .utils import get_lan_ip as _lan

    return _lan(default=None)


_PUBLIC_IP_CACHE: dict[str, tuple[float, str]] = {}
PUBLIC_IP_TTL = 300.0  # reference caches for 5 minutes (nat.py:411-441)

_ECHO_SERVICES = (
    "https://api.ipify.org",
    "https://ifconfig.me/ip",
    "https://icanhazip.com",
    "https://ipinfo.io/ip",
    "https://checkip.amazonaws.com",
    "https://ipecho.net/plain",
)


def get_public_ip(timeout: float = 3.0, use_cache: bool = True) -> str | None:
    """Public IPv4 via HTTPS echo services, falling back to STUN."""
    now = time.monotonic()
    if use_cache:
        hit = _PUBLIC_IP_CACHE.get("ip")
        if hit and now - hit[0] < PUBLIC_IP_TTL:
            return hit[1]
    ip: str | None = None
    try:
        import httpx

        for url in _ECHO_SERVICES:
            try:
                resp = httpx.get(url, timeout=timeout)
                if resp.status_code == 200:
                    candidate = resp.text.strip()
                    socket.inet_aton(candidate)
                    ip = candidate
                    break
            except (httpx.HTTPError, OSError):
                continue
    except ImportError:
        pass
    if ip is None:
        res = STUNClient(timeout=timeout).get_public_endpoint()
        ip = res.ip if res else None
    if ip:
        _PUBLIC_IP_CACHE["ip"] = (now, ip)
    return ip


# ------------------------------------------------------------- forwarder


@dataclass
class PortForwarder:
    """Try each mapping method in order; remember successes for cleanup.

    Order mirrors the reference chain (nat.py:59-64): UPnP (if miniupnpc
    importable) → NAT-PMP → PCP → STUN detection (observe-only).
    """

    gateway: str | None = None
    timeout: float = 2.0
    natpmp_port: int = NATPMP_PORT
    pcp_port: int = PCP_PORT
    mappings: list[PortMapping] = field(default_factory=list)

    def __post_init__(self):
        if self.gateway is None:
            self.gateway = get_gateway_ip()

    def auto_forward(self, port: int, tcp: bool = True) -> PortMapping:
        for attempt in (self._try_upnp, self._try_natpmp, self._try_pcp):
            mapping = attempt(port, tcp)
            if mapping.ok:
                self.mappings.append(mapping)
                return mapping
        mapping = self._try_stun(port)
        if mapping.ok:
            self.mappings.append(mapping)
        return mapping

    # Each _try_* returns a failed PortMapping rather than raising.

    def _try_upnp(self, port: int, tcp: bool) -> PortMapping:
        try:
            import miniupnpc
        except ImportError:
            return PortMapping(False, "upnp", port, detail="miniupnpc not installed")
        try:
            u = miniupnpc.UPnP()
            u.discoverdelay = int(self.timeout * 1000)
            if u.discover() == 0:
                return PortMapping(False, "upnp", port, detail="no IGD found")
            u.selectigd()
            proto = "TCP" if tcp else "UDP"
            if u.addportmapping(port, proto, u.lanaddr, port, "bee2bee_tpu", ""):
                return PortMapping(
                    True, "upnp", port, external_port=port,
                    public_ip=u.externalipaddress(), lifetime=0, tcp=tcp,
                )
            return PortMapping(False, "upnp", port, detail="addportmapping refused")
        except Exception as exc:  # miniupnpc raises bare Exception
            return PortMapping(False, "upnp", port, detail=str(exc))

    def _udp_round_trip(self, packet: bytes, dest: tuple[str, int]) -> bytes | None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            sock.settimeout(self.timeout)
            sock.sendto(packet, dest)
            data, _ = sock.recvfrom(1200)
            return data
        except OSError:
            return None
        finally:
            sock.close()

    def _try_natpmp(self, port: int, tcp: bool) -> PortMapping:
        if not self.gateway:
            return PortMapping(False, "natpmp", port, detail="no gateway")
        dest = (self.gateway, self.natpmp_port)
        data = self._udp_round_trip(build_natpmp_map_request(port, port, tcp=tcp), dest)
        parsed = parse_natpmp_map_response(data) if data else None
        if parsed is None:
            return PortMapping(False, "natpmp", port, detail="no/invalid response")
        _, external, lifetime = parsed
        addr_data = self._udp_round_trip(build_natpmp_public_addr_request(), dest)
        public_ip = parse_natpmp_public_addr_response(addr_data) if addr_data else None
        return PortMapping(
            True, "natpmp", port, external_port=external,
            public_ip=public_ip, lifetime=lifetime, tcp=tcp,
        )

    def _try_pcp(self, port: int, tcp: bool) -> PortMapping:
        if not self.gateway:
            return PortMapping(False, "pcp", port, detail="no gateway")
        client_ip = get_lan_ip() or "0.0.0.0"
        packet, nonce = build_pcp_map_request(client_ip, port, port, tcp=tcp)
        data = self._udp_round_trip(packet, (self.gateway, self.pcp_port))
        parsed = parse_pcp_map_response(data, nonce) if data else None
        if parsed is None:
            return PortMapping(False, "pcp", port, detail="no/invalid response")
        external_port, lifetime, external_ip = parsed
        return PortMapping(
            True, "pcp", port, external_port=external_port,
            public_ip=external_ip, lifetime=lifetime, tcp=tcp, nonce=nonce,
        )

    def _try_stun(self, port: int) -> PortMapping:
        """Observe-only: learns the public address but maps nothing."""
        res = STUNClient(timeout=self.timeout).get_public_endpoint()
        if res is None:
            return PortMapping(False, "none", port, detail="all methods failed")
        return PortMapping(
            True, "stun", port, external_port=res.port, public_ip=res.ip,
            detail="observed via STUN; no mapping created",
        )

    def cleanup(self) -> int:
        """Remove created mappings (zero-lifetime re-request / UPnP delete)."""
        removed = 0
        for m in self.mappings:
            if not m.ok:
                continue
            try:
                if m.method == "upnp":
                    import miniupnpc

                    u = miniupnpc.UPnP()
                    u.discoverdelay = int(self.timeout * 1000)
                    if u.discover() > 0:
                        u.selectigd()
                        u.deleteportmapping(m.external_port, "TCP" if m.tcp else "UDP")
                        removed += 1
                elif m.method == "natpmp" and self.gateway:
                    self._udp_round_trip(
                        build_natpmp_map_request(
                            m.internal_port, 0, lifetime=0, tcp=m.tcp
                        ),
                        (self.gateway, self.natpmp_port),
                    )
                    removed += 1
                elif m.method == "pcp" and self.gateway:
                    packet, _ = build_pcp_map_request(
                        get_lan_ip() or "0.0.0.0", m.internal_port, 0,
                        lifetime=0, tcp=m.tcp, nonce=m.nonce,
                    )
                    self._udp_round_trip(packet, (self.gateway, self.pcp_port))
                    removed += 1
            except Exception:
                continue
        self.mappings = [m for m in self.mappings if not m.ok]
        return removed


def auto_forward_port(port: int, tcp: bool = True) -> PortMapping:
    """One-shot helper mirroring the reference's module-level wrapper
    (reference nat.py:584-609)."""
    if os.environ.get("BEE2BEE_DISABLE_NAT", "").lower() in ("1", "true", "yes"):
        return PortMapping(False, "none", port, detail="disabled by env")
    return PortForwarder().auto_forward(port, tcp=tcp)
