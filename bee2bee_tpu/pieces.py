"""Content-addressed pieces: chunk/hash/verify blobs, and map pieces to
parameter shards on a device mesh.

Capability parity with reference pieces (/root/reference/bee2bee/pieces.py:7-32:
split, per-piece sha256, verify+reassemble, persist). The TPU-native extension
is the *shard manifest*: a piece is not an arbitrary byte range but one
parameter's shard for specific mesh coordinates, so a peer joining a
tensor-parallel serving group can fetch exactly the hash-verified pieces its
mesh position needs (SURVEY §7 hard part 4) and `jax.device_put` them onto
its addressable devices.
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict, dataclass, field
from pathlib import Path

from . import native
from .joinlink import chunk_bytes
from .utils import sha256_hex

DEFAULT_PIECE_SIZE = 4 * 1024 * 1024  # fits the 32 MiB WS frame with headroom


def split_pieces(data: bytes, piece_size: int = DEFAULT_PIECE_SIZE) -> list[bytes]:
    """(reference pieces.py:7-8)"""
    return chunk_bytes(data, piece_size)


def piece_hashes(pieces: list[bytes]) -> list[str]:
    """(reference pieces.py:11-12) — hashed across cores by the C++ codec
    (native.py), hashlib fallback."""
    return native.hash_many(pieces)


def verify_and_reassemble(pieces: list[bytes], hashes: list[str]) -> bytes:
    """Verify each piece hash then concatenate (reference pieces.py:15-21)."""
    if len(pieces) != len(hashes):
        raise ValueError(f"piece/hash count mismatch: {len(pieces)} vs {len(hashes)}")
    bad = native.verify_many(pieces, hashes)
    if bad >= 0:
        got = sha256_hex(pieces[bad])
        raise ValueError(f"piece {bad} hash mismatch: {got[:12]} != {hashes[bad][:12]}")
    return b"".join(pieces)


def save_pieces(pieces: list[bytes], directory: Path | str) -> list[Path]:
    """Persist pieces content-addressed to disk (reference pieces.py:24-32)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    import tempfile

    out = []
    for p in pieces:
        path = directory / sha256_hex(p)
        if not path.exists():
            # mkstemp for a concurrency-safe unique tmp (same pattern as
            # utils.save_json) — a fixed ".tmp" suffix would let two writers
            # interleave and publish corrupt bytes under the content hash
            fd, tmp = tempfile.mkstemp(dir=str(directory), suffix=".tmp")
            with os.fdopen(fd, "wb") as f:
                f.write(p)
            os.replace(tmp, path)
        out.append(path)
    return out


def load_piece(directory: Path | str, digest: str) -> bytes:
    data = (Path(directory) / digest).read_bytes()
    if sha256_hex(data) != digest:
        raise ValueError(f"on-disk piece corrupt: {digest[:12]}")
    return data


# ---- shard manifests ---------------------------------------------------------


@dataclass
class ShardPiece:
    """One parameter-shard piece: which param, which mesh slice, which hash."""

    param: str  # flat param path, e.g. "layers/3/attn/wq"
    shard_index: int  # index along the sharded axis
    shard_count: int  # total shards of this param
    axis: int | None  # tensor axis that is sharded (None = replicated piece)
    mesh_axis: str | None  # mesh axis name ("model", "expert", ...)
    shape: list[int] = field(default_factory=list)  # shard shape
    dtype: str = "bfloat16"
    nbytes: int = 0
    sha256: str = ""


@dataclass
class ShardManifest:
    """Content-addressed description of a fully sharded checkpoint.

    `pieces_for(mesh_axis_index)` returns exactly the pieces a peer at the
    given coordinate on `mesh_axis` must fetch — replicated pieces plus its
    slice of each sharded param.
    """

    model: str
    total_bytes: int = 0
    pieces: list[ShardPiece] = field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(
            {
                "model": self.model,
                "total_bytes": self.total_bytes,
                "pieces": [asdict(p) for p in self.pieces],
            },
            separators=(",", ":"),
        )

    @classmethod
    def from_json(cls, raw: str) -> "ShardManifest":
        obj = json.loads(raw)
        m = cls(model=obj["model"], total_bytes=obj.get("total_bytes", 0))
        m.pieces = [ShardPiece(**p) for p in obj.get("pieces", [])]
        return m

    def pieces_for(self, coords: dict[str, int] | str, index: int | None = None) -> list[ShardPiece]:
        """Pieces a peer at the given mesh coordinates must fetch: replicated
        pieces plus, for every mesh axis the peer has a coordinate on, its
        slice of each param sharded on that axis.

        `coords` is {mesh_axis: index}; the legacy ("axis", i) call form is
        accepted too. Raises if the manifest shards a param on an axis the
        peer supplied no coordinate for — silently dropping those params
        would hand the peer an incomplete checkpoint.
        """
        if isinstance(coords, str):
            coords = {coords: int(index)}  # legacy (mesh_axis, index) form
        out = []
        for p in self.pieces:
            if p.mesh_axis is None:
                out.append(p)
            elif p.mesh_axis in coords:
                if p.shard_index == coords[p.mesh_axis]:
                    out.append(p)
            else:
                raise ValueError(
                    f"param {p.param!r} is sharded on mesh axis {p.mesh_axis!r} "
                    f"but coords only cover {sorted(coords)}"
                )
        return out

    def piece_by_hash(self, digest: str) -> ShardPiece | None:
        for p in self.pieces:
            if p.sha256 == digest:
                return p
        return None


def build_shard_manifest(model: str, params: dict, partition_specs: dict, mesh_axes: dict[str, int]) -> tuple[ShardManifest, dict[str, bytes]]:
    """Shard a flat {path: np.ndarray} param dict per {path: PartitionSpec-like
    tuple} and emit (manifest, {sha256: piece_bytes}).

    `partition_specs[path]` is a tuple with one entry per tensor axis; entries
    are a mesh-axis name or None. Only the first sharded axis is split (one
    level — matches TP-style layouts where each param shards on one axis).
    `mesh_axes` maps axis name → size.
    """
    import numpy as np

    manifest = ShardManifest(model=model)
    blobs: dict[str, bytes] = {}
    pending: list[tuple] = []

    for path in sorted(params):
        arr = np.asarray(params[path])
        spec = tuple(partition_specs.get(path) or ())
        axis = None
        mesh_axis = None
        for i, entry in enumerate(spec):
            if entry is not None:
                axis, mesh_axis = i, entry
                break
        if axis is None or mesh_axes.get(mesh_axis, 1) <= 1:
            shards = [arr]
            axis = mesh_axis = None
        else:
            n = mesh_axes[mesh_axis]
            if arr.shape[axis] % n != 0:
                raise ValueError(
                    f"{path}: axis {axis} size {arr.shape[axis]} not divisible by mesh axis {mesh_axis}={n}"
                )
            shards = np.split(arr, n, axis=axis)
        for idx, shard in enumerate(shards):
            data = np.ascontiguousarray(shard).tobytes()
            pending.append((path, idx, len(shards), axis, mesh_axis, shard, data))

    # one parallel native hashing pass over every shard blob
    digests = native.hash_many([p[-1] for p in pending])
    for (path, idx, count, axis, mesh_axis, shard, data), digest in zip(
        pending, digests
    ):
        blobs[digest] = data
        manifest.pieces.append(
            ShardPiece(
                param=path,
                shard_index=idx,
                shard_count=count,
                axis=axis,
                mesh_axis=mesh_axis,
                shape=list(shard.shape),
                dtype=str(shard.dtype),
                nbytes=len(data),
                sha256=digest,
            )
        )
        manifest.total_bytes += len(data)
    return manifest, blobs


def assemble_params_from_pieces(
    manifest: ShardManifest,
    blobs: dict[str, bytes],
    coords: dict[str, int] | str,
    index: int | None = None,
) -> dict:
    """Rebuild the {path: np.ndarray} shard dict for one mesh coordinate from
    hash-verified piece bytes."""
    import numpy as np

    out: dict = {}
    for p in manifest.pieces_for(coords, index):
        data = blobs.get(p.sha256)
        if data is None:
            raise KeyError(f"missing piece {p.sha256[:12]} for {p.param}")
        if sha256_hex(data) != p.sha256:
            raise ValueError(f"piece corrupt for {p.param}[{p.shard_index}]")
        out[p.param] = np.frombuffer(data, dtype=p.dtype).reshape(p.shape)
    return out
