"""Node configuration: dataclass defaults, JSON persistence, env precedence.

Capability parity with reference config (/root/reference/bee2bee/config.py:11-47):
persisted `~/.bee2bee_tpu/config.json`, env > file > defaults precedence
(reference config.py:35-42). Extended with TPU-specific knobs (mesh shape,
dtype, batch size) that the reference has no analogue for.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field, fields

from .utils import data_file, load_json, save_json

CONFIG_FILE = "config.json"

# env var name -> config field
_ENV_MAP = {
    "BEE2BEE_BOOTSTRAP": "bootstrap_url",
    "BEE2BEE_HOST": "host",
    "BEE2BEE_PORT": "port",
    "BEE2BEE_API_PORT": "api_port",
    "BEE2BEE_ANNOUNCE_HOST": "announce_host",
    "BEE2BEE_ANNOUNCE_PORT": "announce_port",
    "BEE2BEE_API_KEY": "api_key",
    "BEE2BEE_MESH_SHAPE": "mesh_shape",
    "BEE2BEE_DTYPE": "dtype",
    "BEE2BEE_MAX_BATCH": "max_batch_size",
    "BEE2BEE_ATTENTION": "attention",
    "BEE2BEE_PREFILL_CHUNK": "prefill_chunk",
    "BEE2BEE_PREFIX_CACHE": "prefix_cache_entries",
    "BEE2BEE_PAGED": "paged",
    "BEE2BEE_KV_BLOCK_SIZE": "kv_block_size",
    "BEE2BEE_KV_POOL_BLOCKS": "kv_pool_blocks",
    "BEE2BEE_KV_QUANT": "kv_quant",
    "BEE2BEE_SPEC": "spec_tokens",
    "BEE2BEE_DRAFTER": "drafter",
    "BEE2BEE_ADAPTERS": "adapters",
    "BEE2BEE_MAX_ADAPTERS": "max_adapters",
    "BEE2BEE_QUANTIZE": "quantize",
    "BEE2BEE_AUTO_NAT": "auto_nat",
    "BEE2BEE_DHT_PORT": "dht_port",
    "BEE2BEE_DHT_BOOTSTRAP": "dht_bootstrap",
}

_INT_FIELDS = {
    "port", "api_port", "announce_port", "max_batch_size", "max_seq_len",
    "dht_port", "prefill_chunk", "prefix_cache_entries", "kv_block_size",
    "kv_pool_blocks", "spec_tokens", "max_adapters",
}
_BOOL_FIELDS = {"auto_nat", "paged", "kv_quant"}


@dataclass
class NodeConfig:
    """Flat config for one mesh node (serving + networking + compute)."""

    # networking (reference config.py:11-17 defaults)
    bootstrap_url: str = "ws://127.0.0.1:4003"
    host: str = "0.0.0.0"
    port: int = 4003
    api_port: int = 4002
    announce_host: str | None = None
    announce_port: int | None = None
    api_key: str | None = None
    # NAT auto-forwarding on startup (reference p2p_runtime.py:204-261);
    # default off: datacenter TPU hosts don't need it, and it touches the
    # router. Enable via config or BEE2BEE_AUTO_NAT=1.
    auto_nat: bool = False
    # compute (TPU-native additions)
    mesh_shape: str = ""  # e.g. "data:1,model:8" — empty = all devices on model axis
    dtype: str = "bfloat16"
    # attention impl: auto (flash on TPU when the layout supports the
    # kernel, else dense) | dense | flash (pallas kernel) | sp (sequence-
    # parallel serving over a seq-sharded KV cache; needs seq>1 in
    # mesh_shape)
    attention: str = "dense"
    # chunked prefill size (0 = whole-prompt buckets); bounds dense
    # prefill score memory for long prompts (EngineConfig.prefill_chunk)
    prefill_chunk: int = 0
    # prompt prefix cache entries (0 = off): chat turns resend the whole
    # transcript; cached prompt K/V makes turn N+1 prefill only the delta
    prefix_cache_entries: int = 0
    # weight-only quantization: "none" | "int8" (halves decode HBM traffic)
    quantize: str = "none"
    # DEPRECATED no-op (kept so BEE2BEE_PAGED / stored configs parse):
    # the paged block pool is now the engine's only cache layout
    paged: bool = False
    kv_block_size: int = 16  # tokens per pool block (EngineConfig knob)
    # int8 KV pool: pages stored int8 with per-page-per-head scales,
    # dequantized inside the attention kernels — ~2x resident sessions
    # at fixed HBM (BEE2BEE_KV_QUANT / --kv-quant; bf16 pool default)
    kv_quant: bool = False
    # self-speculative decoding: draft up to this many tokens per step
    # by n-gram lookup over the request's own prompt+output and verify
    # them in one batched forward (BEE2BEE_SPEC / --spec; 0 = off —
    # EngineConfig.spec_tokens)
    spec_tokens: int = 0
    # model-tier speculative drafter (BEE2BEE_DRAFTER / --drafter):
    # "" = n-gram tier only; "mesh" = drafts stream from a draft-role
    # peer (BEE2BEE_DISAGG=draft); any other value = a registry model
    # name or checkpoint path loaded resident beside the target. On a
    # draft-role node this names the model the DraftServer hosts.
    # Requires spec_tokens > 0 (EngineConfig.drafter)
    drafter: str = ""
    # batched multi-LoRA serving (adapters/): comma-separated
    # name=path.npz adapters preloaded into the engine's hot-swap pool
    # AND published as pieces manifests on the DHT (BEE2BEE_ADAPTERS /
    # serve-tpu --adapters); empty = none preloaded
    adapters: str = ""
    # adapter pool slots (BEE2BEE_MAX_ADAPTERS): 0 = multi-adapter
    # serving off unless --adapters is given, which implies 8
    max_adapters: int = 0
    # total pool blocks; 0 = default sizing (exhaustion impossible). An
    # explicit smaller value trades HBM for admission backpressure
    # (EngineConfig.kv_pool_blocks)
    kv_pool_blocks: int = 0
    max_batch_size: int = 8  # continuous-batching rows (EngineConfig.max_batch)
    max_seq_len: int = 2048
    max_new_tokens: int = 2048  # reference default (services.py:28)
    price_per_token: float = 0.0
    # DHT for weight distribution (kademlia UDP when installed; reference
    # dht.py:25-38): listen port + comma-separated host:port bootstrap peers
    dht_port: int = 8468
    dht_bootstrap: str = ""
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return asdict(self)

    def engine_config(self):
        """The EngineConfig this node config implies — the ONE place the
        NodeConfig→engine knob mapping (and its 0-means-disabled sentinel
        for prefill_chunk) lives."""
        from .engine.engine import EngineConfig

        return EngineConfig(
            max_seq_len=self.max_seq_len,
            dtype=self.dtype,
            max_batch=self.max_batch_size,
            attention=self.attention,
            prefill_chunk=self.prefill_chunk or None,
            prefix_cache_entries=self.prefix_cache_entries,
            quantize=self.quantize,
            cache_dtype="int8" if self.kv_quant else "bfloat16",
            paged=self.paged,
            kv_block_size=self.kv_block_size,
            kv_pool_blocks=self.kv_pool_blocks or None,
            spec_tokens=self.spec_tokens,
            drafter=self.drafter,
            # --adapters implies a pool even when no slot count was set:
            # the operator clearly wants multi-adapter serving
            max_adapters=self.max_adapters or (8 if self.adapters else 0),
        )


def load_config() -> NodeConfig:
    """defaults <- config.json <- env (highest precedence)."""
    raw = load_json(data_file(CONFIG_FILE), default={}) or {}
    known = {f.name for f in fields(NodeConfig)}
    kwargs = {k: v for k, v in raw.items() if k in known}
    cfg = NodeConfig(**kwargs)
    for env_name, field_name in _ENV_MAP.items():
        val = os.environ.get(env_name)
        if val is not None and val != "":
            if field_name in _INT_FIELDS:
                try:
                    val = int(val)
                except ValueError:
                    continue
            elif field_name in _BOOL_FIELDS:
                val = val.lower() in ("1", "true", "yes", "on")
            setattr(cfg, field_name, val)
    return cfg


def save_config(cfg: NodeConfig) -> None:
    save_json(data_file(CONFIG_FILE), cfg.to_dict())


def get_bootstrap_url() -> str:
    return load_config().bootstrap_url


def set_bootstrap_url(url: str) -> None:
    cfg = load_config()
    cfg.bootstrap_url = url
    save_config(cfg)


def parse_mesh_shape(spec: str) -> dict[str, int]:
    """Parse "data:1,model:8" → {"data": 1, "model": 8}. Empty → {}."""
    out: dict[str, int] = {}
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, n = part.partition(":")
        out[name.strip()] = int(n)
    return out
