"""Python client SDK for bee2bee-tpu nodes and web gateways.

The reference ships a JS client SDK (/root/reference/app/src/api/index.js)
that targets a v1 API the shipped gateway never implemented (SURVEY §2.2
"aspirational"). This SDK targets the REAL shipped surfaces:

- ``NodeClient`` — a node's own HTTP gateway (api.py): status / peers /
  providers / connect / chat with streaming, X-API-KEY auth.
- ``GatewayClient`` — the web tier (web/gateway.py): register join link,
  streamed generate, mesh status, global metrics.

Both are thin aiohttp wrappers with sync convenience methods so scripts
and notebooks don't need an event loop. Tested against live in-process
servers in tests/test_client.py.
"""

from __future__ import annotations

import asyncio
import json
import random
import time
from contextlib import asynccontextmanager
from typing import AsyncIterator, Callable

import aiohttp

DEFAULT_TIMEOUT_S = 300.0  # matches the mesh request timeout
# idempotent-GET retry policy: transient CONNECTION failures (refused /
# reset / dropped mid-flight — aiohttp.ClientConnectionError) retry with
# exponential backoff + jitter, and typed 429/503 overload answers retry
# honoring the server's Retry-After (bounded by MAX_RETRY_AFTER_S and the
# client's own deadline). POSTs never retry (a generate may have
# executed); non-overload HTTP error statuses never retry (they're
# answers).
DEFAULT_GET_RETRIES = 2
DEFAULT_RETRY_BACKOFF_S = 0.2
MAX_RETRY_AFTER_S = 30.0  # cap on honoring a server's Retry-After hint


class MeshOverloaded(RuntimeError):
    """Typed 429/503 from a node's admission controller (docs/SERVING.md):
    the node is shedding, not broken. Carries the machine-readable
    rejection so callers can back off intelligently instead of parsing
    an HTTP error string."""

    def __init__(self, message: str, status: int,
                 error_kind: str | None = None,
                 retry_after_s: float | None = None):
        super().__init__(message)
        self.status = status
        self.error_kind = error_kind
        self.retry_after_s = retry_after_s


async def _raise_if_overloaded(r) -> None:
    """Map a 429/503 response onto MeshOverloaded, folding in the typed
    body (error_kind / retry_after_s) and the Retry-After header."""
    if r.status not in (429, 503):
        return
    kind, retry_after, detail = None, None, f"HTTP {r.status}"
    try:
        body = await r.json()
        err = body.get("error") if isinstance(body.get("error"), dict) else body
        kind = err.get("error_kind")
        if err.get("retry_after_s") is not None:
            retry_after = float(err["retry_after_s"])
        detail = err.get("detail") or err.get("message") or detail
    except Exception:  # noqa: BLE001 — a proxy's bare 503 has no JSON body
        pass
    if retry_after is None:
        hdr = r.headers.get("Retry-After")
        if hdr is not None:
            try:
                retry_after = float(hdr)
            except ValueError:
                pass
    raise MeshOverloaded(
        f"mesh overloaded ({detail})", r.status,
        error_kind=kind, retry_after_s=retry_after,
    )


class _Base:
    """Use as an async context manager (`async with NodeClient(...) as c:`)
    to hold one pooled keep-alive session across calls; outside it, each
    call opens an ephemeral session (sessions are loop-bound, and the sync
    wrappers run each call on a fresh loop)."""

    def __init__(self, base_url: str, timeout: float = DEFAULT_TIMEOUT_S,
                 retries: int = DEFAULT_GET_RETRIES,
                 retry_backoff_s: float = DEFAULT_RETRY_BACKOFF_S):
        self.base_url = base_url.rstrip("/")
        self.timeout = aiohttp.ClientTimeout(total=timeout)
        self.retries = retries
        self.retry_backoff_s = retry_backoff_s
        self._headers: dict[str, str] = {}
        self._session: aiohttp.ClientSession | None = None

    async def __aenter__(self):
        self._session = aiohttp.ClientSession(timeout=self.timeout)
        return self

    async def __aexit__(self, *exc):
        if self._session is not None:
            await self._session.close()
            self._session = None

    @asynccontextmanager
    async def _sess(self):
        if self._session is not None and not self._session.closed:
            yield self._session
        else:
            async with aiohttp.ClientSession(timeout=self.timeout) as s:
                yield s

    async def _get(self, path: str, **params) -> dict:
        """GETs are idempotent: transient connection errors retry with
        exponential backoff + jitter, and typed 429/503 overload answers
        retry honoring the server's Retry-After (jittered, capped) —
        both bounded by self.retries AND by the client's configured total
        timeout, so retrying never multiplies the caller's time budget
        (slow failures give up early)."""
        total = self.timeout.total
        deadline = (time.monotonic() + total) if total else None
        attempt = 0
        while True:
            try:
                return await self._get_once(path, **params)
            except (aiohttp.ClientConnectionError, MeshOverloaded) as e:
                attempt += 1
                delay = (self.retry_backoff_s * 2 ** (attempt - 1)
                         * (1.0 + random.random() * 0.25))
                if isinstance(e, MeshOverloaded) and e.retry_after_s:
                    # honor the server's hint, jittered so a shed burst
                    # doesn't return in lockstep; capped so a hostile or
                    # misconfigured hint can't park the client
                    delay = max(delay, min(
                        e.retry_after_s * (1.0 + random.random() * 0.25),
                        MAX_RETRY_AFTER_S,
                    ))
                if attempt > self.retries or (
                    deadline is not None
                    and time.monotonic() + delay >= deadline
                ):
                    raise
                await asyncio.sleep(delay)

    async def _get_once(self, path: str, **params) -> dict:
        async with self._sess() as s:
            async with s.get(
                f"{self.base_url}{path}", headers=self._headers,
                params={k: v for k, v in params.items() if v is not None},
            ) as r:
                await _raise_if_overloaded(r)
                r.raise_for_status()
                return await r.json()

    async def _post(self, path: str, body: dict) -> dict:
        """POSTs never retry (a generate may have executed) — but a typed
        429/503 still surfaces as MeshOverloaded so callers get the
        rejection kind and Retry-After instead of a bare HTTP error."""
        async with self._sess() as s:
            async with s.post(
                f"{self.base_url}{path}", json=body, headers=self._headers
            ) as r:
                await _raise_if_overloaded(r)
                r.raise_for_status()
                return await r.json()

    def _run(self, coro):
        """Sync convenience: run the coroutine on a private loop."""
        return asyncio.run(coro)


class NodeClient(_Base):
    """Client for one node's HTTP gateway (api.py routes)."""

    def __init__(self, base_url: str, api_key: str | None = None,
                 timeout: float = DEFAULT_TIMEOUT_S, **kw):
        super().__init__(base_url, timeout, **kw)
        if api_key:
            self._headers["X-API-KEY"] = api_key

    # ---- async API ----

    async def status(self) -> dict:
        return await self._get("/")

    async def peers(self) -> dict:
        return await self._get("/peers")

    async def providers(self) -> dict:
        return await self._get("/providers")

    async def connect(self, addr_or_link: str) -> dict:
        return await self._post("/connect", {"addr": addr_or_link})

    async def chat(
        self,
        prompt: str,
        model: str | None = None,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        **sampling,  # top_k/top_p/min_p/repetition_penalty/presence_penalty/
        # frequency_penalty — forwarded verbatim (api.py passes them to
        # the service layer and over the P2P wire)
    ) -> dict:
        # sampling spreads FIRST: reserved keys (prompt/model/stream)
        # always win, so a typo'd or malicious kwarg can't flip the
        # request shape out from under the response parser
        body = {**sampling, "prompt": prompt, "model": model, "stream": False}
        if max_new_tokens is not None:
            body["max_new_tokens"] = max_new_tokens
        if temperature is not None:
            body["temperature"] = temperature
        return await self._post("/chat", body)

    async def stream(
        self,
        prompt: str,
        model: str | None = None,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        **sampling,
    ) -> AsyncIterator[dict]:
        """Yield the JSON-lines objects of a streamed generation
        ({"text": piece} chunks, then {"done": true, ...})."""
        body = {**sampling, "prompt": prompt, "model": model, "stream": True}
        if max_new_tokens is not None:
            body["max_new_tokens"] = max_new_tokens
        if temperature is not None:
            body["temperature"] = temperature
        async with self._sess() as s:
            async with s.post(
                f"{self.base_url}/chat", json=body, headers=self._headers
            ) as r:
                await _raise_if_overloaded(r)
                r.raise_for_status()
                async for line in r.content:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except ValueError:
                        continue

    # ---- sync conveniences ----

    def status_sync(self) -> dict:
        return self._run(self.status())

    def chat_sync(self, prompt: str, **kw) -> dict:
        return self._run(self.chat(prompt, **kw))

    def generate_sync(
        self, prompt: str, on_chunk: Callable[[str], None] | None = None, **kw
    ) -> str:
        """Stream a generation, invoking on_chunk per text piece; returns
        the full text."""

        async def run():
            parts: list[str] = []
            async for obj in self.stream(prompt, **kw):
                if obj.get("text"):
                    parts.append(obj["text"])
                    if on_chunk:
                        on_chunk(obj["text"])
                if obj.get("status") == "error":
                    raise RuntimeError(obj.get("message") or "stream error")
            return "".join(parts)

        return self._run(run())


class GatewayClient(_Base):
    """Client for the web tier (web/gateway.py /api/p2p/* routes).

    ``generate(..., with_meta=True)`` asks the gateway for its response
    metadata trailer; the parsed dict (tokens / cost / latency_ms and the
    node's per-request ``timing`` breakdown) lands on ``self.last_meta``
    and is stripped from the returned text."""

    last_meta: dict | None = None

    async def status(self) -> dict:
        return await self._get("/api/p2p/status")

    async def global_metrics(self) -> dict:
        return await self._get("/api/p2p/global_metrics")

    async def register(self, join_link: str) -> dict:
        return await self._post("/api/p2p/register", {"link": join_link})

    async def generate(
        self,
        prompt: str,
        model: str | None = None,
        target_node: str | None = None,
        on_chunk: Callable[[str], None] | None = None,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        with_meta: bool = False,
    ) -> str:
        """Streamed generate through the gateway; returns the full text.
        (The gateway streams raw text chunks, not JSON lines.)"""
        # reset FIRST: an errored call must not leave the previous call's
        # meta readable as if it belonged to this one
        self.last_meta = None
        body: dict = {"prompt": prompt, "model": model}
        if target_node:
            body["targetNode"] = target_node
        if max_new_tokens is not None:
            body["max_new_tokens"] = max_new_tokens
        if temperature is not None:
            body["temperature"] = temperature
        if with_meta:
            body["meta"] = True
        import codecs

        decoder = codecs.getincrementaldecoder("utf-8")(errors="replace")
        parts: list[str] = []
        meta_marker = "\n\n[Meta]: "
        acc = ""  # decoded so far (for the on_chunk trailer scrub)
        emitted = 0  # chars of acc already handed to on_chunk

        def forward_clean() -> None:
            """Feed on_chunk only text that cannot belong to the [Meta]
            trailer: stop at a full marker, and hold back any tail that is
            a prefix of it (markers can split across stream chunks)."""
            nonlocal emitted
            idx = acc.find(meta_marker, max(0, emitted - len(meta_marker)))
            if idx != -1:
                safe = idx
            else:
                safe = len(acc)
                for k in range(min(len(meta_marker), len(acc)), 0, -1):
                    if meta_marker.startswith(acc[len(acc) - k:]):
                        safe = len(acc) - k
                        break
            if safe > emitted:
                on_chunk(acc[emitted:safe])
                emitted = safe

        async with self._sess() as s:
            async with s.post(
                f"{self.base_url}/api/p2p/generate", json=body,
                headers=self._headers,
            ) as r:
                r.raise_for_status()
                async for chunk in r.content.iter_any():
                    # incremental decode: a multi-byte UTF-8 sequence split
                    # across chunks must not become U+FFFD
                    text = decoder.decode(chunk)
                    if text:
                        parts.append(text)
                        if on_chunk:
                            if with_meta:
                                acc += text
                                forward_clean()
                            else:
                                on_chunk(text)
                tail = decoder.decode(b"", final=True)
                if tail:
                    parts.append(tail)
        full = "".join(parts)
        # the gateway reports failures INSIDE the already-200 stream
        # (web/gateway.py appends "\n\n[Error]: ..."): surface them as
        # errors, with any partial output attached
        marker = "\n\n[Error]: "
        idx = full.rfind(marker)
        if idx != -1:
            err = RuntimeError(f"gateway error: {full[idx + len(marker):].strip()}")
            err.partial_text = full[:idx]
            raise err
        # response metadata trailer (same in-stream convention): parse it
        # off the text and keep it on last_meta for the caller
        idx = full.rfind(meta_marker)
        if idx != -1:
            try:
                self.last_meta = json.loads(full[idx + len(meta_marker):])
                full = full[:idx]
            except ValueError:
                pass  # not ours: leave the text untouched
        if on_chunk and with_meta and emitted < len(full):
            # flush whatever forward_clean held back — a marker-prefix
            # lookalike at stream end (e.g. the text just ends in "\n\n",
            # or the gateway never sent a trailer), an in-text marker
            # occurrence before the real trailer, or the decoder's final
            # tail — so the streamed view equals the returned text
            on_chunk(full[emitted:])
        return full

    def status_sync(self) -> dict:
        return self._run(self.status())

    def generate_sync(self, prompt: str, **kw) -> str:
        return self._run(self.generate(prompt, **kw))
