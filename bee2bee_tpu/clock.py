"""The Clock seam: every time source the coordination layer reads.

The mesh's behavior is saturated with wall-clock reads — ping cadence,
HealthStore TTLs, lease boot-grace and lapse timers, SLO burn windows,
incident cooldowns, admission queue timeouts, drain deadlines. Each bare
`time.time()` / `asyncio.sleep()` in those paths is a place the fleet
simulation (`bee2bee_tpu/simnet/`) cannot reach: a 200-node chaos run
would take real minutes per lease TTL and its traces would never be
reproducible. This module is the single seam all of them route through.

Injection contract (docs/SIMULATION.md has the long form):

- `Clock` is the interface: `time()`, `monotonic()`, `sleep()`,
  `wait_for()`. `SystemClock` is the production implementation and
  delegates straight to `time` / `asyncio`.
- Components that own a clock take a `clock=` constructor argument
  defaulting to `None` → "resolve the process-global clock". `P2PNode`
  threads its clock into everything it constructs (HealthStore,
  SloTracker, LeaseKeeper, FleetController, AdmissionController).
- Process-global singletons that outlive any one node (the flight
  recorder, module-level helpers) resolve `get_clock()` *at call time*,
  never at import/construction time, so a simulation installing a
  virtual clock with `set_clock()` takes effect everywhere at once.
- `asyncio.wait_for` is a wall-clock leak too — its timeout rides the
  real event-loop timer — so the seam includes `Clock.wait_for()`.
  `SystemClock` delegates to `asyncio.wait_for`; the generic base
  implementation races the awaitable against `self.sleep(timeout)` so a
  virtual clock's timeouts fire in virtual time.

The meshlint pass ML-C001 (analysis/clockseam.py) keeps this seam from
eroding: direct wall-clock calls inside the seamed packages are findings
unless carrying a reasoned `# meshlint: ignore[ML-C001]`.
"""

from __future__ import annotations

import asyncio
import time as _time
from typing import Any, Awaitable


class Clock:
    """Time-source interface. Subclasses must provide `time`, `monotonic`
    and `sleep`; `wait_for` has a generic implementation that only relies
    on `sleep`, so virtual clocks get virtual timeouts for free."""

    def time(self) -> float:
        raise NotImplementedError

    def monotonic(self) -> float:
        raise NotImplementedError

    async def sleep(self, delay: float) -> None:
        raise NotImplementedError

    async def wait_for(self, awaitable: Awaitable[Any], timeout: float | None) -> Any:
        """`asyncio.wait_for` semantics on this clock's timeline: returns
        the awaitable's result, or cancels it and raises
        `asyncio.TimeoutError` once `timeout` elapses *on this clock*."""
        task = asyncio.ensure_future(awaitable)
        if timeout is None:
            return await task
        timer = asyncio.ensure_future(self.sleep(timeout))
        try:
            done, _ = await asyncio.wait(
                {task, timer}, return_when=asyncio.FIRST_COMPLETED
            )
            if task in done:
                return task.result()
            task.cancel()
            # consume the cancellation so it never surfaces as "exception
            # was never retrieved" — mirrors asyncio.wait_for's own cleanup
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            raise asyncio.TimeoutError
        finally:
            if not timer.done():
                timer.cancel()
                try:
                    await timer
                except asyncio.CancelledError:
                    pass


class SystemClock(Clock):
    """Production clock: real wall time, real event-loop timers."""

    def time(self) -> float:
        return _time.time()

    def monotonic(self) -> float:
        return _time.monotonic()

    async def sleep(self, delay: float) -> None:
        await asyncio.sleep(delay)

    async def wait_for(self, awaitable: Awaitable[Any], timeout: float | None) -> Any:
        return await asyncio.wait_for(awaitable, timeout)


_SYSTEM = SystemClock()
_CLOCK: Clock = _SYSTEM


def get_clock() -> Clock:
    """The process-global clock. SystemClock unless a simulation (or test)
    installed a replacement via `set_clock`."""
    return _CLOCK


def set_clock(clock: Clock | None) -> Clock:
    """Install `clock` process-wide (None restores the system clock).
    Returns the previously installed clock so callers can restore it."""
    global _CLOCK
    prev = _CLOCK
    _CLOCK = clock if clock is not None else _SYSTEM
    return prev


def resolve_clock(clock: Clock | None) -> Clock:
    """The standard `clock=` ctor-argument resolution: explicit wins,
    None means the process-global clock *as of now*."""
    return clock if clock is not None else _CLOCK
