"""Global registry client: node liveness/models/metrics upserts to a Supabase
REST `active_nodes` table, or a cluster entrypoint relay (reference
registry.py:10-69 + SUPABASE_SCHEMA.sql:66-76). Enabled iff env creds are
present; all failures are soft (the mesh works without a registry)."""

from __future__ import annotations

import asyncio
import logging
import os
import time

logger = logging.getLogger("bee2bee_tpu.registry")

SYNC_INTERVAL_S = 30.0


class RegistryClient:
    def __init__(
        self,
        supabase_url: str | None = None,
        supabase_key: str | None = None,
        entrypoint: str | None = None,
    ):
        self.supabase_url = supabase_url or os.environ.get("SUPABASE_URL") or os.environ.get(
            "VITE_SUPABASE_URL"
        )
        self.supabase_key = supabase_key or os.environ.get("SUPABASE_ANON_KEY") or os.environ.get(
            "VITE_SUPABASE_ANON_KEY"
        )
        self.entrypoint = entrypoint or os.environ.get("BEE2BEE_ENTRYPOINT")
        self.mode = (
            "supabase"
            if (self.supabase_url and self.supabase_key)
            else ("entrypoint" if self.entrypoint else None)
        )

    @property
    def enabled(self) -> bool:
        return self.mode is not None

    def _node_record(self, node) -> dict:
        models = []
        for svc in node.local_services.values():
            models.extend(svc.get_metadata().get("models", []))
        return {
            "node_id": node.peer_id,
            "address": node.addr,
            "region": node.region,
            "models": models,
            "metrics": node.status()["metrics"],
            "api_port": node.api_port,
            "last_seen": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }

    async def sync_node(self, node) -> bool:
        """One upsert; returns success. Soft-fails on any network error."""
        if not self.enabled:
            return False
        import httpx

        record = self._node_record(node)
        try:
            async with httpx.AsyncClient(timeout=10) as client:
                if self.mode == "supabase":
                    r = await client.post(
                        f"{self.supabase_url.rstrip('/')}/rest/v1/active_nodes",
                        json=record,
                        headers={
                            "apikey": self.supabase_key,
                            "Authorization": f"Bearer {self.supabase_key}",
                            "Content-Type": "application/json",
                            # upsert-on-conflict (reference registry.py:62-66)
                            "Prefer": "resolution=merge-duplicates",
                        },
                    )
                else:
                    r = await client.post(
                        f"{self.entrypoint.rstrip('/')}/register", json=record
                    )
                return r.status_code < 300
        except Exception as e:
            logger.debug("registry sync failed: %s", e)
            return False

    async def fetch_nodes(self) -> list[dict]:
        """Read the global mesh (bridge.js syncGlobalMesh equivalent)."""
        if self.mode != "supabase":
            return []
        import httpx

        try:
            async with httpx.AsyncClient(timeout=10) as client:
                r = await client.get(
                    f"{self.supabase_url.rstrip('/')}/rest/v1/active_nodes",
                    params={"select": "*"},
                    headers={
                        "apikey": self.supabase_key,
                        "Authorization": f"Bearer {self.supabase_key}",
                    },
                )
                if r.status_code < 300:
                    return r.json()
        except Exception as e:
            logger.debug("registry fetch failed: %s", e)
        return []

    def _client(self):
        """Long-lived AsyncClient for per-request paths (record_message
        runs per generation — a fresh pool + TLS handshake each time would
        sit on the serving hot path). Lazy; closed via aclose()."""
        import httpx

        if getattr(self, "_http", None) is None or self._http.is_closed:
            self._http = httpx.AsyncClient(timeout=10)
        return self._http

    async def aclose(self):
        if getattr(self, "_http", None) is not None and not self._http.is_closed:
            await self._http.aclose()

    async def record_message(
        self,
        node_id: str,
        tokens: int,
        role: str = "assistant",
        cost: float = 0.0,
        user_id: str | None = None,
    ) -> bool:
        """Token + cost accounting insert into the `messages` table (the
        web gateway's per-generation accounting — reference index.js:65-86
        writes user_id/cost rows; cost here is the node-computed
        price_per_token x tokens from services/base.py result_dict)."""
        if self.mode != "supabase":
            return False
        try:
            row = {
                "node_id": node_id,
                "content": "[metric log]",
                "role": role,
                "tokens": int(tokens),
                "cost": float(cost or 0.0),
            }
            if user_id:
                row["user_id"] = user_id
            r = await self._client().post(
                f"{self.supabase_url.rstrip('/')}/rest/v1/messages",
                json=row,
                headers={
                    "apikey": self.supabase_key,
                    "Authorization": f"Bearer {self.supabase_key}",
                    "Content-Type": "application/json",
                },
            )
            return r.status_code < 300
        except Exception as e:
            logger.debug("registry message write failed: %s", e)
            return False

    async def sync_loop(self, node, interval_s: float = SYNC_INTERVAL_S):
        while True:
            await self.sync_node(node)
            await asyncio.sleep(interval_s)
