"""simnet — deterministic in-process fleet simulation.

Runs hundreds of FakeService-backed `P2PNode` control planes (no
engines, no sockets, no wall clock) in ONE process, faster than real
time, with bit-identical event traces across same-seed replays:

- `VirtualClock` (clock.py): a timer-heap clock injected through the
  `bee2bee_tpu.clock` seam. `run_for(60)` advances 60 virtual seconds
  in however many milliseconds the pending work actually takes.
- `SimNet` / `SimTransport` (transport.py): a virtual network injected
  through the `bee2bee_tpu.transport` seam. Seeded per-link latency,
  loss, and partitionable regions; delivery order is a pure function
  of the seed.
- `FleetSim` (harness.py): builds an N-node mesh on both seams,
  bootstraps it, runs scripted chaos scenarios, and extracts the event
  trace + `/fleet` decision journals for replay comparison.
- `dht.py`: a pure-data Kademlia model for lookup-depth scaling claims
  (the in-memory DHT the mesh ships has no routed lookup to measure).
- `fuzz.py`: the seeded interleaving fuzzer — replays scenarios under
  perturbed-but-legal schedules and flags outcome divergence, dropped
  generations, and unhandled task exceptions (the dynamic half of the
  raceguard; see analysis/raceguard.py for the static half).

See docs/SIMULATION.md for the seam design and determinism contract.
"""

from .clock import VirtualClock
from .dht import KademliaModel
from .fuzz import FuzzFinding, SchedulePerturbation, fuzz
from .harness import FleetSim, SimService
from .transport import LinkProfile, SimNet, SimTransport

__all__ = [
    "FleetSim",
    "FuzzFinding",
    "KademliaModel",
    "LinkProfile",
    "SchedulePerturbation",
    "SimNet",
    "SimService",
    "SimTransport",
    "VirtualClock",
    "fuzz",
]
