"""SimNet: the in-process virtual network behind the Transport seam.

One `SimNet` is one universe: a listener table keyed (host, port), a
seeded `random.Random` that decides every latency jitter and loss roll,
per-link latency/loss profiles, partitionable regions, and the event
trace. Each node gets a `SimTransport` bound to its own virtual host
("10.0.x.y") so `P2PNode.addr` resolves without touching the real
network stack.

Determinism contract (docs/SIMULATION.md):

- All delivery happens on `VirtualClock.call_at` timers, never directly:
  even a zero-latency universe orders frames by (deadline, registration
  seq), which the single-threaded loop replays identically.
- Per-connection FIFO is preserved (`delivery_t = max(prev_t, …)`) —
  a websocket is an ordered stream and the real mesh never sees
  intra-link reorder. *Cross*-link reorder emerges from jitter, which is
  the reorder that actually happens in production.
- Delivery times quantize UP to a coarse grid (`quantum_s`) so the
  thousands of frames of a ping tick land in a handful of timer batches
  instead of thousands — the difference between a 200-node tick costing
  milliseconds and costing minutes — while the (deadline, seq) order
  stays seed-deterministic.
- The RNG is consumed in scheduling order only, so a replay draws the
  identical stream.

Partitions black-hole frames (TCP stalls, it doesn't RST) and refuse
new dials; `heal()` restores both. Loss drops individual frames. Both
are recorded in the trace (`drop` / `part` events) so a chaos run's
story is auditable.
"""

from __future__ import annotations

import math
import random
import re
from collections import deque
from dataclasses import dataclass

from .. import wscompat
from ..transport import Transport
from .clock import VirtualClock

#: protocol.msg puts "type" first and protocol.encode is plain
#: json.dumps, so the op name sits in the frame's first few bytes
_OP_RE = re.compile(r'"type":\s*"([a-z_]+)"')


def frame_op(raw: str | bytes) -> str:
    if isinstance(raw, bytes):
        head = raw[:120].decode("utf-8", "replace")
    else:
        head = raw[:120]
    m = _OP_RE.search(head)
    return m.group(1) if m else "?"


@dataclass
class LinkProfile:
    """Delivery model for one directed link (or a default for all).

    Keep `jitter_s` a few multiples of the net's `quantum_s`: jitter is
    what lets the seed pick *which* delivery batch a frame lands in —
    jitter smaller than one quantum rounds away entirely and every seed
    replays the same schedule."""

    latency_s: float = 0.002
    jitter_s: float = 0.012
    loss: float = 0.0


class SimNet:
    def __init__(
        self,
        clock: VirtualClock,
        seed: int = 0,
        default_profile: LinkProfile | None = None,
        quantum_s: float = 0.005,
        trace_enabled: bool = True,
    ):
        self.clock = clock
        self.rng = random.Random(seed)
        self.seed = seed
        self.quantum_s = quantum_s
        self.default_profile = default_profile or LinkProfile()
        self.trace_enabled = trace_enabled
        self._listeners: dict[tuple[str, int], SimServer] = {}
        #: (src_host, dst_host) -> LinkProfile overrides
        self.links: dict[tuple[str, str], LinkProfile] = {}
        #: host -> region name ("default" unless assigned)
        self.regions: dict[str, str] = {}
        #: blocked region pairs (frozenset of two names)
        self._partitions: set[frozenset] = set()
        #: (t, kind, src_host, dst_host, op, size) — the replay-compared
        #: event record. kinds: dial / frame / drop / part / close
        self.trace: list[tuple] = []
        self.frames_delivered = 0
        self.frames_dropped = 0
        #: optional SchedulePerturbation (simnet.fuzz): adds whole extra
        #: delivery quanta and forced send-point yields. None = canonical.
        self.perturb = None

    # ------------------------------------------------------------ topology

    def set_region(self, host: str, region: str) -> None:
        self.regions[host] = region

    def set_link(self, src_host: str, dst_host: str, profile: LinkProfile) -> None:
        self.links[(src_host, dst_host)] = profile

    def partition(self, region_a: str, region_b: str) -> None:
        self._partitions.add(frozenset((region_a, region_b)))

    def heal(self, region_a: str | None = None, region_b: str | None = None) -> None:
        if region_a is None:
            self._partitions.clear()
        else:
            self._partitions.discard(frozenset((region_a, region_b)))

    def partitioned(self, src_host: str, dst_host: str) -> bool:
        if not self._partitions:
            return False
        a = self.regions.get(src_host, "default")
        b = self.regions.get(dst_host, "default")
        return frozenset((a, b)) in self._partitions

    def profile(self, src_host: str, dst_host: str) -> LinkProfile:
        return self.links.get((src_host, dst_host), self.default_profile)

    # ------------------------------------------------------------ plumbing

    def transport(self, host: str) -> "SimTransport":
        """The per-node Transport: binds every serve/dial to `host` so
        links know their endpoints."""
        return SimTransport(self, host)

    def record(self, kind: str, src: str, dst: str, op: str = "", size: int = 0):
        if self.trace_enabled:
            self.trace.append(
                (round(self.clock.time(), 6), kind, src, dst, op, size)
            )

    def _delivery_time(self, conn: "SimConn", size: int) -> float | None:
        """Schedule one frame on `conn`: returns the virtual delivery
        time, or None when the frame is lost/partitioned. Consumes the
        RNG in scheduling order — part of the determinism contract."""
        prof = self.profile(conn.src_host, conn.dst_host)
        jitter = self.rng.random() * prof.jitter_s
        lost = prof.loss > 0 and self.rng.random() < prof.loss
        if self.partitioned(conn.src_host, conn.dst_host):
            self.record("part", conn.src_host, conn.dst_host, size=size)
            self.frames_dropped += 1
            return None
        if lost:
            self.record("drop", conn.src_host, conn.dst_host, size=size)
            self.frames_dropped += 1
            return None
        t = self.clock.time() + prof.latency_s + jitter
        # quantize UP so batches share deadlines; FIFO via prev-time clamp
        q = self.quantum_s
        if q > 0:
            t = math.ceil(t / q) * q
            if self.perturb is not None:
                # whole extra quanta shift a frame into a later delivery
                # batch; applied before the FIFO clamp so per-conn order
                # is preserved — only *cross*-link interleaving changes
                t += self.perturb.extra_quanta() * q
        return max(t, conn.last_delivery_t)

    # ------------------------------------------------------------ dial/serve

    def open(self, src_host: str, dst_host: str, dst_port: int,
             max_size: int | None) -> "SimConn":
        server = self._listeners.get((dst_host, dst_port))
        if server is None or server.closed:
            raise OSError(f"sim: connection refused {dst_host}:{dst_port}")
        if self.partitioned(src_host, dst_host):
            raise OSError(f"sim: unreachable {src_host} -> {dst_host} (partition)")
        client = SimConn(self, src_host, dst_host, max_size)
        remote = SimConn(self, dst_host, src_host, server.max_size)
        client.peer = remote
        remote.peer = client
        self.record("dial", src_host, dst_host)
        server.accept(remote)
        return client

    def listen(self, host: str, port: int, handler, max_size: int | None) -> "SimServer":
        key = (host, port)
        if key in self._listeners and not self._listeners[key].closed:
            raise OSError(f"sim: address in use {host}:{port}")
        server = SimServer(self, host, port, handler, max_size)
        self._listeners[key] = server
        return server


class _SimSocket:
    """Just enough socket for `server.sockets[0].getsockname()`."""

    def __init__(self, host: str, port: int):
        self._addr = (host, port)

    def getsockname(self):
        return self._addr


class SimServer:
    def __init__(self, net: SimNet, host: str, port: int, handler,
                 max_size: int | None):
        import asyncio

        self._asyncio = asyncio
        self.net = net
        self.host = host
        self.port = port
        self.handler = handler
        self.max_size = max_size
        self.closed = False
        self.sockets = [_SimSocket(host, port)]
        self.conns: list[SimConn] = []
        self._tasks: list = []

    def accept(self, conn: "SimConn") -> None:
        self.conns.append(conn)
        task = self._asyncio.get_running_loop().create_task(self._run(conn))
        self._tasks.append(task)
        task.add_done_callback(self._tasks.remove)

    async def _run(self, conn: "SimConn") -> None:
        try:
            await self.handler(conn)
        finally:
            conn.abort()

    def close(self) -> None:
        """wscompat contract: kills the listener AND established conns."""
        self.closed = True
        self.net._listeners.pop((self.host, self.port), None)
        for conn in list(self.conns):
            conn.abort()

    async def wait_closed(self) -> None:
        tasks = list(self._tasks)
        if tasks:
            await self._asyncio.gather(*tasks, return_exceptions=True)


class SimConn:
    """One direction-pair endpoint. Mirrors the wscompat/websockets slice
    the mesh uses: send/recv/close, async iteration ending on any close,
    `wscompat.exceptions.ConnectionClosed` on dead-peer operations."""

    def __init__(self, net: SimNet, src_host: str, dst_host: str,
                 max_size: int | None):
        import asyncio

        self._asyncio = asyncio
        self.net = net
        self.src_host = src_host
        self.dst_host = dst_host
        self.max_size = max_size
        self.peer: SimConn | None = None
        self.closed = False  # local end: send() refused
        self.recv_closed = False  # remote FIN delivered: recv() drains then raises
        self.last_delivery_t = 0.0  # FIFO clamp for frames *we* send
        self._queue: deque = deque()
        self._waiter = None

    # ---------------------------------------------------------------- send

    async def send(self, data: str | bytes) -> None:
        if self.net.perturb is not None and self.net.perturb.should_yield():
            # forced task switch at an instrumented await point: models a
            # loop that schedules another runnable task before this send
            # proceeds. The liveness checks below re-run after the switch,
            # exactly as real code must tolerate.
            await self._asyncio.sleep(0)
        if self.closed or self.peer is None:
            raise wscompat.ConnectionClosedError("sim connection is closed")
        size = len(data) if isinstance(data, bytes) else len(data.encode("utf-8"))
        if self.peer.max_size and size > self.peer.max_size:
            raise wscompat.ConnectionClosedError(
                f"sim frame of {size} bytes exceeds max_size"
            )
        t = self.net._delivery_time(self, size)
        if t is None:
            return  # lost or partitioned: the bytes just never arrive
        self.last_delivery_t = t
        peer = self.peer
        op = frame_op(data)
        src, dst = self.src_host, self.dst_host

        def deliver(data=data, op=op, size=size):
            if peer.recv_closed:
                return  # arrived after the receiver died
            self.net.record("frame", src, dst, op, size)
            self.net.frames_delivered += 1
            peer._queue.append(data)
            peer._wake()

        self.net.clock.call_at(t, deliver)

    # ---------------------------------------------------------------- recv

    def _wake(self) -> None:
        if self._waiter is not None and not self._waiter.done():
            self._waiter.set_result(None)

    async def recv(self) -> str | bytes:
        while True:
            if self._queue:
                return self._queue.popleft()
            if self.recv_closed:
                raise wscompat.ConnectionClosed("sim connection closed")
            self._waiter = self._asyncio.get_running_loop().create_future()
            try:
                await self._waiter
            finally:
                self._waiter = None

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self.recv()
        except wscompat.ConnectionClosed:
            raise StopAsyncIteration

    # ---------------------------------------------------------------- close

    async def close(self) -> None:
        """Graceful close: stop sending now; the peer sees EOF after the
        frames already in flight (FIFO with data, like a real FIN)."""
        if self.closed:
            return
        self.closed = True
        self.net.record("close", self.src_host, self.dst_host)
        peer = self.peer
        if peer is None or peer.recv_closed:
            return
        t = max(self.net.clock.time(), self.last_delivery_t)

        def fin():
            peer.recv_closed = True
            peer.closed = True
            peer._wake()

        self.net.clock.call_at(t, fin)

    def abort(self) -> None:
        """Hard kill both directions immediately (server shutdown, chaos
        hard_kill): queued frames still drain, nothing new arrives."""
        self.closed = True
        self.recv_closed = True
        self._wake()
        if self.peer is not None and not self.peer.recv_closed:
            self.peer.closed = True
            self.peer.recv_closed = True
            self.peer._wake()


class SimTransport(Transport):
    """The Transport seam's sim backend: one per node, bound to the
    node's virtual host. Reuses wscompat's exception family so the
    mesh's except clauses need no sim-awareness."""

    name = "sim"
    exceptions = wscompat.exceptions

    def __init__(self, net: SimNet, host: str):
        self.net = net
        self.host = host

    async def dial(self, addr: str, *, max_size: int | None = None,
                   open_timeout: float = 10):
        m = re.match(r"wss?://([^:/]+):(\d+)", addr)
        if not m:
            raise OSError(f"sim: bad address {addr!r}")
        return self.net.open(self.host, m.group(1), int(m.group(2)), max_size)

    async def serve(self, handler, host: str, port: int, *,
                    max_size: int | None = None):
        # nodes bind "0.0.0.0"; the universe knows us by our virtual host
        bind = self.host if host in ("0.0.0.0", "::", "localhost") else host
        return self.net.listen(bind, port, handler, max_size)
