"""VirtualClock: a timer-heap clock for deterministic fleet simulation.

Time only moves when the harness says so. `sleep(d)` parks the caller on
a heap keyed `(deadline, seq)`; `run_for(duration)` pops due timers in
that order, fires them, and lets the event loop settle between batches.
With a single-threaded loop and strictly ordered timers, the schedule —
and therefore every downstream decision the mesh makes — is a pure
function of the program and the SimNet seed. A 200-node fleet burns
through minutes of lease TTLs and ping cadences in wall-clock
milliseconds.

Two timer kinds share the heap:

- futures (from `sleep`) — resolved in order; a cancelled sleeper is
  skipped, so `node.stop()`'s task cancellation composes.
- callbacks (from `call_at`) — SimNet schedules one per frame delivery
  without paying for a task per message.

`wait_for` is inherited from the generic `Clock` base: it races the
awaitable against `self.sleep(timeout)`, so timeouts fire in virtual
time too (a lease-acquire timeout set to 30 s expires after 30 *virtual*
seconds, instantly in wall time).
"""

from __future__ import annotations

import asyncio
import heapq
from typing import Callable

from ..clock import Clock


class VirtualClock(Clock):
    def __init__(self, start: float = 1_700_000_000.0, settle_idle_rounds: int = 25):
        # an epoch-plausible start keeps time.time()-shaped consumers
        # (digest "ts" fields, journal timestamps) in a familiar range
        self._now = float(start)
        self._seq = 0
        # heap of (deadline, bias, seq, future-or-callback); bias is 0.0
        # except when an interleaving perturbation (simnet.fuzz) biases
        # same-deadline sleeper order to explore alternative schedules
        self._timers: list[tuple[float, float, int, object]] = []
        #: optional SchedulePerturbation (simnet.fuzz); None = canonical
        #: (deadline, seq) order, bit-identical to the unperturbed clock
        self.perturb = None
        # settle() returns after this many consecutive loop passes during
        # which no new timer was registered: passes where nothing is ready
        # cost ~µs, so the threshold buys safety for deep await chains
        # (lock → handler → send → …) without a per-batch tax that scales
        # with fleet size
        self.settle_idle_rounds = settle_idle_rounds

    # ------------------------------------------------------------ Clock API

    def time(self) -> float:
        return self._now

    def monotonic(self) -> float:
        return self._now

    async def sleep(self, delay: float) -> None:
        if delay is None or delay <= 0:
            await asyncio.sleep(0)
            return
        fut = asyncio.get_running_loop().create_future()
        self._push(self._now + float(delay), fut)
        await fut

    # ------------------------------------------------------------ scheduling

    def _push(self, deadline: float, item: object) -> None:
        self._seq += 1
        bias = 0.0
        if self.perturb is not None and isinstance(item, asyncio.Future):
            # only sleepers get biased: delivery callbacks keep FIFO
            # registration order (a websocket is an ordered stream), so a
            # perturbed schedule is still one the real network could produce
            bias = self.perturb.sleep_bias()
        heapq.heappush(self._timers, (deadline, bias, self._seq, item))

    def call_at(self, when: float, fn: Callable[[], None]) -> None:
        """Run `fn` when virtual time reaches `when` (synchronously, in
        timer order). For plain-function effects like frame delivery —
        no task, no future."""
        self._push(max(when, self._now), fn)

    def pending_timers(self) -> int:
        return len(self._timers)

    def next_deadline(self) -> float | None:
        return self._timers[0][0] if self._timers else None

    # ------------------------------------------------------------ advancing

    async def settle(self) -> None:
        """Yield to the event loop until it quiesces: every runnable task
        has run to its next timer-wait (or completion) and no new timers
        appeared for `settle_idle_rounds` consecutive passes."""
        idle = 0
        while idle < self.settle_idle_rounds:
            before = self._seq
            await asyncio.sleep(0)
            idle = idle + 1 if self._seq == before else 0

    async def run_for(self, duration: float) -> None:
        """Advance virtual time by `duration` seconds, firing every timer
        that falls due, in (deadline, registration-order) order."""
        target = self._now + float(duration)
        await self.settle()
        while self._timers and self._timers[0][0] <= target:
            deadline = self._timers[0][0]
            if deadline > self._now:
                self._now = deadline
            fired = False
            while self._timers and self._timers[0][0] <= self._now:
                _, _, _, item = heapq.heappop(self._timers)
                if isinstance(item, asyncio.Future):
                    if not item.done():  # skip cancelled sleepers
                        item.set_result(None)
                        fired = True
                else:
                    item()  # delivery callback
                    fired = True
            if fired:
                await self.settle()
        self._now = target
        await self.settle()
