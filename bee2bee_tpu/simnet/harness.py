"""FleetSim: N P2PNode control planes on one loop, virtual everything.

The harness owns the two seams end to end: it installs a `VirtualClock`
process-wide (`set_clock`) so call-time resolvers (flight recorder,
digest builders, dataclass defaults) follow the simulation, hands every
node a per-host `SimTransport` into one seeded `SimNet`, zeroes the
metrics registry so telemetry digests start from the same bytes every
run, and restores the previous clock on `stop()`.

Scenario vocabulary:

- `run_for(seconds)` — advance virtual time (wall cost: only the work).
- `drive(coro)` — await a mesh future (a generation, a drain) by
  advancing time deadline-by-deadline until it resolves.
- `kill(i)` / `add_node()` — churn, process-death semantics via
  `meshnet.chaos.hard_kill`.
- `net.partition(a, b)` / `net.heal()` — region split-brain.
- `trace_fingerprint()` / `journal_fingerprint()` — the replay
  comparison surface: same seed ⇒ bit-identical strings.

Determinism checklist baked in (docs/SIMULATION.md): metrics sampling
off (`ping_metrics_enabled=False` — psutil digits would differ between
replays), services answer on the loop (`SimService.execute_async` — an
executor thread would race the schedule), registry reset between runs
(digest counter values are part of frame bytes), uuid-derived ids are
fixed-width so frame *sizes* stay replay-stable even though id bytes
differ.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
from typing import Any

from ..clock import set_clock
from ..meshnet.chaos import hard_kill
from ..meshnet.node import P2PNode
from ..metrics import get_registry
from ..services.base import ServiceError
from ..services.fake import FakeService
from .clock import VirtualClock
from .transport import LinkProfile, SimNet


class SimService(FakeService):
    """FakeService that answers on the event loop in virtual time.

    The base class's `execute()` runs in the node's executor (a real
    thread — its interleaving would poison the deterministic schedule)
    and stamps wall-clock latencies into the result (frame bytes that
    differ between replays). `execute_async` keeps the whole request on
    the loop with clock-derived, replay-stable timings."""

    def __init__(self, clock=None, **kw):
        super().__init__(**kw)
        self._clock = clock

    async def execute_async(self, params: dict[str, Any]) -> dict[str, Any]:
        self.calls.append(dict(params))
        if self.fail_with:
            raise ServiceError(self.fail_with)
        if self.exec_delay_s and self._clock is not None:
            await self._clock.sleep(self.exec_delay_s)
        text = self._reply_for(params)
        n = len(text.split())
        lat_ms = int(self.exec_delay_s * 1000.0)
        return {
            "text": text,
            "tokens": n,
            "latency_ms": lat_ms,
            "price_per_token": self.price_per_token,
            "cost": self.price_per_token * n,
            "timing": {
                "queue_wait_ms": 0.0,
                "prefill_ms": float(lat_ms),
                "ttft_ms": float(lat_ms),
                "decode_tokens": n,
                "tokens_per_s": 0.0,
                "spec_acceptance": None,
            },
        }


class FleetSim:
    def __init__(
        self,
        n: int,
        seed: int = 0,
        controllers: int = 1,
        ping_interval_s: float = 1.0,
        regions: dict[int, str] | None = None,
        profile: LinkProfile | None = None,
        quantum_s: float = 0.005,
        with_service: bool = True,
        trace_enabled: bool = True,
        perturb=None,
    ):
        self.clock = VirtualClock()
        self.net = SimNet(
            self.clock, seed=seed, default_profile=profile,
            quantum_s=quantum_s, trace_enabled=trace_enabled,
        )
        # interleaving fuzzer hook (simnet.fuzz.SchedulePerturbation):
        # biases same-deadline sleeper order, stretches delivery times by
        # whole quanta, and forces yields at send points. None = canonical
        # deterministic schedule.
        self.clock.perturb = perturb
        self.net.perturb = perturb
        self.n = n
        self.seed = seed
        self.controllers = controllers
        self.ping_interval_s = ping_interval_s
        self.regions = dict(regions or {})
        self.with_service = with_service
        self.nodes: list[P2PNode] = []
        self.dead: set[str] = set()
        self._prev_clock = None
        self._started = False

    # ------------------------------------------------------------ build

    @staticmethod
    def host_for(i: int) -> str:
        return f"10.0.{i // 250}.{i % 250 + 1}"

    def build_node(self, i: int) -> P2PNode:
        host = self.host_for(i)
        region = self.regions.get(i, "default")
        self.net.set_region(host, region)
        node = P2PNode(
            host=host,
            port=9000,
            region=region,
            node_id=f"sim-{i:04d}",
            fleet_controller=(i < self.controllers),
            clock=self.clock,
            transport=self.net.transport(host),
        )
        node.ping_metrics_enabled = False
        if self.ping_interval_s is not None:
            # re-derive the cadence-coupled TTLs the ctor computed from
            # the production default (health TTL and lease TTL are both
            # "3 ticks" — the ratio is the contract, not the seconds)
            node.ping_interval_s = self.ping_interval_s
            node.health.ttl_s = 3.0 * self.ping_interval_s
            node.fleet.lease.ttl_s = 3.0 * self.ping_interval_s
        if self.with_service:
            node.add_service(SimService(clock=self.clock, model_name="sim-model"))
        return node

    # ------------------------------------------------------------ lifecycle

    async def start(self, bootstrap: bool = True) -> "FleetSim":
        self._prev_clock = set_clock(self.clock)
        self._started = True
        # zero shared-registry counters: telemetry digests carry their
        # values, and a replay must produce the same frame bytes
        get_registry().reset_all()
        for i in range(self.n):
            self.nodes.append(self.build_node(i))
        for node in list(self.nodes):  # snapshot: add_node() appends mid-start
            await node.start()
        if bootstrap:
            await self.bootstrap()
        return self

    async def stop(self) -> None:
        if not self._started:
            return
        for node in reversed(self.nodes):
            if node.peer_id in self.dead:
                continue
            with contextlib.suppress(Exception):
                await node.stop()
        await self.clock.settle()
        self._started = False
        set_clock(self._prev_clock)

    async def __aenter__(self) -> "FleetSim":
        return await self.start()

    async def __aexit__(self, *exc) -> None:
        await self.stop()

    # ------------------------------------------------------------ bootstrap

    async def bootstrap(self, max_virtual_s: float = 60.0) -> float:
        """Join everyone through node 0 (hello → peer_list → fan-out
        dials) and advance time until the mesh is fully connected.
        Returns the virtual seconds it took."""
        t0 = self.clock.time()
        seed_addr = self.nodes[0].addr
        for node in self.nodes[1:]:
            await node._connect_peer(seed_addr)  # noqa: SLF001 — harness
        deadline = t0 + max_virtual_s
        while not self.mesh_connected():
            if self.clock.time() >= deadline:
                raise RuntimeError(
                    f"bootstrap stalled at peer counts {self.peer_counts()}"
                )
            await self._advance_one_deadline()
        return self.clock.time() - t0

    async def _advance_one_deadline(self) -> None:
        nxt = self.clock.next_deadline()
        if nxt is None:
            await self.clock.settle()
            if self.clock.next_deadline() is None:
                raise RuntimeError("simulation deadlock: no pending timers")
            nxt = self.clock.next_deadline()
        await self.clock.run_for(max(nxt - self.clock.time(), 0.0))

    async def run_for(self, seconds: float) -> None:
        await self.clock.run_for(seconds)

    # ------------------------------------------------------------ inspection

    def alive(self) -> list[P2PNode]:
        return [n for n in self.nodes if n.peer_id not in self.dead]

    def peer_counts(self) -> list[int]:
        return [len(n.peers) for n in self.alive()]

    def mesh_connected(self) -> bool:
        want = len(self.alive()) - 1
        return all(len(n.peers) >= want for n in self.alive())

    def gossip_coverage(self) -> float:
        """Fraction of (observer, subject) pairs where the observer holds
        a FRESH telemetry digest for the subject. 1.0 = converged."""
        alive = self.alive()
        if len(alive) < 2:
            return 1.0
        want = {n.peer_id for n in alive}
        got = 0
        for n in alive:
            fresh = set(n.health.fresh().keys())
            got += len(fresh & (want - {n.peer_id}))
        return got / (len(alive) * (len(alive) - 1))

    def journals(self) -> dict[str, list[dict]]:
        """Every controller-enabled node's fleet decision journal."""
        return {
            n.peer_id: [dict(e) for e in n.fleet.decisions]
            for n in self.nodes
            if n.fleet.enabled
        }

    def journal_fingerprint(self) -> str:
        return json.dumps(self.journals(), sort_keys=True, default=str)

    def trace_fingerprint(self) -> str:
        return json.dumps(self.net.trace)

    # ------------------------------------------------------------ scenario verbs

    async def drive(self, coro, max_virtual_s: float = 300.0):
        """Await a mesh future (a generation, a drain, a migration) by
        advancing virtual time deadline-by-deadline until it resolves."""
        task = asyncio.ensure_future(coro)
        await self.clock.settle()
        deadline = self.clock.time() + max_virtual_s
        while not task.done() and self.clock.time() < deadline:
            await self._advance_one_deadline()
        if not task.done():
            task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await task
            raise TimeoutError(
                f"drive(): not resolved after {max_virtual_s} virtual s"
            )
        return task.result()

    async def kill(self, i: int) -> None:
        """Process-death: sockets die, no GOODBYE, node stops responding."""
        node = self.nodes[i]
        self.dead.add(node.peer_id)
        await hard_kill(node)
        await self.clock.settle()

    async def add_node(self) -> P2PNode:
        """Grow the fleet by one (churn scenarios). Joins through node 0's
        address; caller advances time until it melds in."""
        i = len(self.nodes)
        node = self.build_node(i)
        self.nodes.append(node)
        self.n += 1
        await node.start()
        await node._connect_peer(self.nodes[0].addr)  # noqa: SLF001
        return node
