"""Seeded interleaving fuzzer: the dynamic half of the raceguard.

meshlint's ML-R0xx passes (analysis/raceguard.py) find interleaving
hazards statically; this module *provokes* them. It replays simnet
scenarios under N perturbed-but-still-legal schedules and reports any
run whose observable outcome differs from the canonical deterministic
baseline — plus any unhandled task exception or dropped generation the
perturbation shakes loose.

Perturbation model — three knobs, all schedules the real network could
produce (per-connection FIFO is never violated):

- **sleeper tie-break bias** (`VirtualClock._push`): same-deadline
  sleepers are reordered among themselves. Delivery callbacks keep
  registration order — a websocket is an ordered stream.
- **extra delivery quanta** (`SimNet._delivery_time`): a frame lands
  0..`max_extra_quanta` batches later than its jitter draw said,
  applied *before* the per-conn FIFO clamp. Only cross-link
  interleaving changes.
- **forced yields** (`SimConn.send`): `await asyncio.sleep(0)` at the
  send point with probability `yield_prob` — the "another task ran
  first" schedule that check-then-act bugs need.

Every perturbed run is itself deterministic: one `SchedulePerturbation`
is one seeded RNG consumed in scheduling order, so any finding replays
from `(scenario, net_seed, schedule_seed)` alone:

    python -m bee2bee_tpu.simnet.fuzz --scenario toctou_demo \
        --net-seed 0 --schedules 20

Divergence is judged on a schedule-INDEPENDENT outcome digest per
scenario (leader counts after failover, generations completed, drain
summaries) — raw event traces legitimately differ across schedules;
outcomes must not. `toctou_demo` is the deliberately raceable control:
its check-then-act grant booth diverges under perturbation (and its
source trips ML-R001 when the suppression below is stripped), proving
both halves of the raceguard see the same bug.
"""

from __future__ import annotations

import argparse
import asyncio
import gc
import json
import random
from dataclasses import asdict, dataclass

from .clock import VirtualClock
from .harness import FleetSim
from .transport import LinkProfile, SimNet


class SchedulePerturbation:
    """One seeded source of schedule noise, consumed in scheduling order.

    Installed on a `VirtualClock` (`.perturb`) and a `SimNet`
    (`.perturb`); `FleetSim(perturb=...)` wires both. The same
    (net_seed, schedule_seed) pair always replays the same run."""

    def __init__(self, seed: int, yield_prob: float = 0.25,
                 max_extra_quanta: int = 2):
        self.seed = seed
        self.yield_prob = yield_prob
        self.max_extra_quanta = max_extra_quanta
        self._rng = random.Random(seed)

    def sleep_bias(self) -> float:
        """Tie-break key for same-deadline sleepers (VirtualClock._push)."""
        return self._rng.random()

    def extra_quanta(self) -> int:
        """Whole delivery batches to delay one frame (SimNet._delivery_time)."""
        return self._rng.randrange(self.max_extra_quanta + 1)

    def should_yield(self) -> bool:
        """Force a task switch at this send point (SimConn.send)."""
        return self._rng.random() < self.yield_prob


@dataclass(frozen=True)
class FuzzFinding:
    """One interleaving bug, replayable from its coordinates.

    kinds: outcome_divergence (perturbed outcome != baseline),
    unhandled_exception (loop exception handler fired),
    dropped_generation (a generation the scenario started never
    completed), replay_divergence (two UNperturbed runs disagreed —
    the determinism contract itself is broken)."""

    kind: str
    scenario: str
    net_seed: int
    schedule: int | None  # SchedulePerturbation seed; None = baseline run
    detail: str


# ------------------------------------------------------------- scenarios
#
# A scenario is `async (net_seed, perturb) -> outcome dict`. The dict
# must be SCHEDULE-INDEPENDENT: invariants (counts, booleans, the only
# possible survivor) — never timestamps, traces, or timing-dependent
# identities. A `_dropped` key (list) is stripped by the runner and
# reported as dropped_generation findings instead of compared.


async def _scenario_fleet_election(net_seed: int, perturb) -> dict:
    """Leader failover: kill the sitting leader, the surviving
    controller must claim the lease — exactly one leader, same identity,
    under every schedule."""
    sim = FleetSim(5, seed=net_seed, controllers=2, perturb=perturb)
    try:
        await sim.start()
        await sim.run_for(6.0)  # past the claim stagger: a leader exists
        initial = [
            n.peer_id for n in sim.alive()
            if n.fleet.enabled and n.fleet.is_leader
        ]
        await sim.kill(0)  # the rank-0 claimant — process death, no GOODBYE
        await sim.run_for(15.0)  # > 3-tick lease TTL + claim stagger
        after = [
            n.peer_id for n in sim.alive()
            if n.fleet.enabled and n.fleet.is_leader
        ]
        return {
            "initial_leaders": len(initial),
            "failover_leaders": len(after),
            # only one controller survives the kill, so the identity is
            # schedule-independent too
            "failover_leader": after[0] if len(after) == 1 else None,
            "mesh_connected": sim.mesh_connected(),
        }
    finally:
        await sim.stop()


async def _scenario_drain_migrate(net_seed: int, perturb) -> dict:
    """Drain with a generation in flight: `begin_drain` must wait for
    the in-flight request, the generation must complete, and the node
    must end up draining — under every schedule."""
    sim = FleetSim(4, seed=net_seed, perturb=perturb)
    fut = None
    try:
        await sim.start()
        prov = sim.nodes[2]
        prov.local_services["fake"].exec_delay_s = 2.0
        fut = asyncio.ensure_future(
            sim.nodes[1].request_generation(
                prov.peer_id, "drain-me", model="sim-model", timeout=60.0
            )
        )
        await sim.run_for(0.5)  # request on the wire, provider mid-decode
        in_flight_when_drained = not fut.done()
        summary = await sim.drive(prov.begin_drain())
        await sim.run_for(5.0)
        dropped = []
        gen_ok = False
        if fut.done() and not fut.cancelled() and fut.exception() is None:
            gen_ok = bool(fut.result().get("text"))
        if not gen_ok:
            state = (
                "pending" if not fut.done()
                else repr(fut.exception() or fut.result())
            )
            dropped.append(f"generation 'drain-me' did not complete: {state}")
        return {
            "in_flight_when_drained": in_flight_when_drained,
            "gen_completed": gen_ok,
            "draining": bool(prov.draining),
            "drain_summary_ok": isinstance(summary, dict),
            "_dropped": dropped,
        }
    finally:
        if fut is not None and not fut.done():
            fut.cancel()
        await sim.stop()


async def _scenario_churn(net_seed: int, perturb) -> dict:
    """Hard-kill bystanders while generations are in flight on the
    survivors: every generation completes, the controller keeps
    journaling — under every schedule."""
    sim = FleetSim(8, seed=net_seed, perturb=perturb)
    futs: list = []
    try:
        await sim.start()
        pairs = [(1, 2), (3, 4)]
        for _, b in pairs:
            sim.nodes[b].local_services["fake"].exec_delay_s = 2.0
        futs = [
            asyncio.ensure_future(
                sim.nodes[a].request_generation(
                    sim.nodes[b].peer_id, f"p-{k}",
                    model="sim-model", timeout=60.0,
                )
            )
            for k, (a, b) in enumerate(pairs)
        ]
        await sim.run_for(0.4)  # requests in flight
        for i in (6, 7):  # bystander churn: hard kills, no GOODBYE
            await sim.kill(i)
        await sim.run_for(10.0)
        dropped = []
        done = 0
        for k, f in enumerate(futs):
            ok = (
                f.done() and not f.cancelled() and f.exception() is None
                and bool(f.result().get("text"))
            )
            if ok:
                done += 1
            else:
                state = (
                    "pending" if not f.done()
                    else repr(f.exception() if f.exception() else f.result())
                )
                dropped.append(f"generation 'p-{k}' did not complete: {state}")
        journaled = sum(len(v) for v in sim.journals().values())
        return {
            "generations_completed": done,
            "controller_journaled": journaled > 0,
            "_dropped": dropped,
        }
    finally:
        for f in futs:
            if not f.done():
                f.cancel()
        await sim.stop()


class _GrantBooth:
    """Deliberately raceable exclusive-grant server: the fuzzer's
    seeded TOCTOU. `handle` checks `self.holder`, awaits grant
    bookkeeping, then writes it — the textbook ML-R001 shape. Under the
    canonical schedule the second request arrives after the first grant
    lands (one grant); a perturbed schedule that parks both requests
    inside the bookkeeping window double-grants."""

    def __init__(self, clock: VirtualClock):
        self.clock = clock
        self.holder: str | None = None
        self.grants: list[str] = []

    async def handle(self, ws) -> None:
        async for who in ws:
            if self.holder is None:
                # the suspension point that makes the check stale
                await self.clock.sleep(0.004)
                self.holder = who  # meshlint: ignore[ML-R001] -- deliberate raceable demo: the fuzzer must catch this dynamically and raceguard statically (tests strip this suppression and re-run the pass)
                self.grants.append(who)
                await ws.send("granted")
            else:
                await ws.send("busy")


async def _scenario_toctou_demo(net_seed: int, perturb) -> dict:
    """Two clients race for one grant, staggered so the canonical
    schedule serializes them. Jitter is zeroed: the ONLY schedule noise
    is the perturbation, so baseline yields exactly one grant for every
    net_seed and any double-grant is the fuzzer's doing."""
    clock = VirtualClock()
    clock.perturb = perturb
    net = SimNet(
        clock, seed=net_seed,
        default_profile=LinkProfile(latency_s=0.002, jitter_s=0.0, loss=0.0),
    )
    net.perturb = perturb
    booth = _GrantBooth(clock)
    server = await net.transport("10.0.0.1").serve(
        booth.handle, "0.0.0.0", 9000
    )
    alpha = await net.transport("10.0.0.2").dial("ws://10.0.0.1:9000")
    beta = await net.transport("10.0.0.3").dial("ws://10.0.0.1:9000")
    replies: dict[str, str] = {}

    async def acquire(ws, name: str, delay_s: float) -> None:
        await clock.sleep(delay_s)
        await ws.send(name)
        replies[name] = await ws.recv()

    tasks = [
        asyncio.ensure_future(acquire(alpha, "alpha", 0.0)),
        # 6 ms stagger: baseline arrival (5 ms batch + 4 ms window) has
        # beta landing at 10 ms, after alpha's grant at 9 ms. One extra
        # delivery quantum on alpha (or one fewer... there are none on
        # beta's side to remove — only alpha slipping a batch) overlaps
        # the windows.
        asyncio.ensure_future(acquire(beta, "beta", 0.006)),
    ]
    try:
        await clock.run_for(1.0)
        return {
            "grants": len(booth.grants),
            "replied": sorted(replies),
        }
    finally:
        for t in tasks:
            if not t.done():
                t.cancel()
        await alpha.close()
        await beta.close()
        server.close()
        await clock.run_for(0.5)


SCENARIOS = {
    "fleet_election": _scenario_fleet_election,
    "drain_migrate": _scenario_drain_migrate,
    "churn": _scenario_churn,
    "toctou_demo": _scenario_toctou_demo,
}

#: scenarios that must be fuzz-clean (toctou_demo is the deliberately
#: broken control — it PASSES by diverging)
CLEAN_SCENARIOS = ("fleet_election", "drain_migrate", "churn")


# ------------------------------------------------------------- the runner


@dataclass
class RunResult:
    outcome: dict
    dropped: list
    exceptions: list


def _run_scenario(fn, net_seed: int, perturb) -> RunResult:
    """One scenario run on a fresh loop, unhandled task exceptions
    captured via the loop's exception handler (plus a gc pass so
    dropped-handle exceptions surface before the loop dies)."""
    exceptions: list[str] = []

    def on_exception(loop, context) -> None:
        exc = context.get("exception")
        detail = (
            f"{type(exc).__name__}: {exc}" if exc is not None
            else str(context.get("message", "unknown"))
        )
        exceptions.append(detail)

    async def main():
        asyncio.get_running_loop().set_exception_handler(on_exception)
        try:
            out = await fn(net_seed, perturb)
        except Exception as exc:
            # a stalled bootstrap / crashed scenario IS an outcome — it
            # diverges from the baseline instead of killing the sweep
            out = {"scenario_error": f"{type(exc).__name__}: {exc}"}
        # surface exceptions held by about-to-be-collected tasks NOW,
        # while the handler is still the one we installed
        gc.collect()
        await asyncio.sleep(0)
        return out

    outcome = asyncio.run(main())
    gc.collect()  # late task finalizers still route to our handler
    dropped = outcome.pop("_dropped", [])
    return RunResult(outcome, dropped, exceptions)


def _harvest(result: RunResult, scenario: str, net_seed: int,
             schedule: int | None, findings: list) -> None:
    for exc in result.exceptions:
        findings.append(FuzzFinding(
            "unhandled_exception", scenario, net_seed, schedule, exc,
        ))
    for d in result.dropped:
        findings.append(FuzzFinding(
            "dropped_generation", scenario, net_seed, schedule, d,
        ))


def fuzz(scenario: str, net_seed: int = 0, schedules: int = 20,
         yield_prob: float = 0.25, max_extra_quanta: int = 2,
         ) -> list[FuzzFinding]:
    """Replay `scenario` under `schedules` perturbed schedules and
    return every finding. Empty list = interleaving-clean."""
    fn = SCENARIOS[scenario]
    findings: list[FuzzFinding] = []

    baseline = _run_scenario(fn, net_seed, None)
    _harvest(baseline, scenario, net_seed, None, findings)
    if "scenario_error" in baseline.outcome:
        findings.append(FuzzFinding(
            "unhandled_exception", scenario, net_seed, None,
            f"baseline run failed: {baseline.outcome['scenario_error']}",
        ))
    replay = _run_scenario(fn, net_seed, None)
    if replay.outcome != baseline.outcome:
        findings.append(FuzzFinding(
            "replay_divergence", scenario, net_seed, None,
            f"unperturbed replay disagreed: {baseline.outcome!r} "
            f"!= {replay.outcome!r}",
        ))

    for k in range(1, schedules + 1):
        perturb = SchedulePerturbation(
            k, yield_prob=yield_prob, max_extra_quanta=max_extra_quanta,
        )
        r = _run_scenario(fn, net_seed, perturb)
        _harvest(r, scenario, net_seed, k, findings)
        if r.outcome != baseline.outcome:
            findings.append(FuzzFinding(
                "outcome_divergence", scenario, net_seed, k,
                f"{r.outcome!r} != baseline {baseline.outcome!r}",
            ))
    return findings


# ------------------------------------------------------------------ CLI


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bee2bee_tpu.simnet.fuzz",
        description="seeded interleaving fuzzer over simnet scenarios",
    )
    ap.add_argument(
        "--scenario", default="clean",
        choices=sorted(SCENARIOS) + ["clean", "all"],
        help="one scenario, 'clean' (all fuzz-clean scenarios), or 'all'",
    )
    ap.add_argument("--net-seed", type=int, default=0)
    ap.add_argument("--schedules", type=int, default=20,
                    help="perturbed schedules per scenario")
    ap.add_argument("--yield-prob", type=float, default=0.25)
    ap.add_argument("--max-extra-quanta", type=int, default=2)
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.scenario == "clean":
        names = list(CLEAN_SCENARIOS)
    elif args.scenario == "all":
        names = sorted(SCENARIOS)
    else:
        names = [args.scenario]

    all_findings: list[FuzzFinding] = []
    for name in names:
        found = fuzz(
            name, net_seed=args.net_seed, schedules=args.schedules,
            yield_prob=args.yield_prob,
            max_extra_quanta=args.max_extra_quanta,
        )
        all_findings.extend(found)
        if not args.as_json:
            print(f"{name}: {args.schedules} schedules, "
                  f"{len(found)} finding(s)")
            for f in found:
                where = (
                    "baseline" if f.schedule is None
                    else f"schedule {f.schedule}"
                )
                print(f"  [{f.kind}] net_seed={f.net_seed} {where}: "
                      f"{f.detail}")
    if args.as_json:
        print(json.dumps([asdict(f) for f in all_findings], indent=2))
    return 1 if all_findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
