"""KademliaModel: a pure-data Kademlia routing model for scaling claims.

The mesh's shipped DHT (`bee2bee_tpu/dht.py`) is either an in-memory
dict (no routing at all) or the external `kademlia` package (real UDP —
unusable for a deterministic 500-peer depth measurement). This model
implements just the routing math — 160-bit XOR metric, k-buckets,
iterative α-parallel lookup — over seeded ids, so the sim can answer
"how many hops does a lookup take at N peers?" with exact, replayable
numbers. Expected depth is O(log₂ N/k)-ish; the regression test pins
the measured depth envelope so a routing-table regression (or a future
real implementation that diverges from Kademlia's contract) shows up as
a failed assertion instead of a production latency cliff.

No wire, no clock: one lookup round = one hop. Determinism comes from
`random.Random(seed)` ids and sorted candidate selection.
"""

from __future__ import annotations

import hashlib
import random

ID_BITS = 160


def _node_id(rng: random.Random) -> int:
    # hash a seeded draw so ids spread uniformly over the full space
    # regardless of the rng's internal structure
    return int.from_bytes(
        hashlib.sha1(rng.getrandbits(64).to_bytes(8, "big")).digest(), "big"
    )


class KademliaModel:
    def __init__(self, n_peers: int, seed: int = 0, k: int = 20, alpha: int = 3):
        self.k = k
        self.alpha = alpha
        rng = random.Random(seed)
        self.rng = rng
        ids = set()
        while len(ids) < n_peers:
            ids.add(_node_id(rng))
        self.peers = sorted(ids)
        #: peer id -> routing table: bucket index -> [peer ids], k-capped.
        #: Build order is seeded (shuffled join order), so which of the
        #: >k candidates make it into a full bucket is replay-stable.
        self.tables: dict[int, dict[int, list[int]]] = {p: {} for p in self.peers}
        join_order = list(self.peers)
        rng.shuffle(join_order)
        for i, p in enumerate(join_order):
            # a joining peer and the existing network learn of each other
            for q in join_order[:i]:
                self._insert(p, q)
                self._insert(q, p)

    @staticmethod
    def bucket_index(a: int, b: int) -> int:
        return (a ^ b).bit_length() - 1  # -1 never queried (a != b)

    def _insert(self, owner: int, other: int) -> None:
        if owner == other:
            return
        bucket = self.tables[owner].setdefault(self.bucket_index(owner, other), [])
        if other not in bucket and len(bucket) < self.k:
            bucket.append(other)

    def closest_known(self, owner: int, target: int, limit: int) -> list[int]:
        known = [q for b in self.tables[owner].values() for q in b]
        known.sort(key=lambda q: q ^ target)
        return known[:limit]

    def lookup_depth(self, origin: int, target: int, max_hops: int = 64) -> int:
        """Iterative FIND_NODE: query the α closest unqueried candidates
        each round until the k-closest set stops improving. Returns the
        number of rounds (hops) — the latency-determining figure."""
        shortlist = self.closest_known(origin, target, self.k)
        queried: set[int] = set()
        hops = 0
        while hops < max_hops:
            batch = [q for q in shortlist if q not in queried][: self.alpha]
            if not batch:
                break
            hops += 1
            queried.update(batch)
            improved = False
            merged = set(shortlist)
            for q in batch:
                merged.update(self.closest_known(q, target, self.k))
            new_shortlist = sorted(merged, key=lambda q: q ^ target)[: self.k]
            if new_shortlist != shortlist:
                improved = True
            shortlist = new_shortlist
            if not improved:
                break
        return hops

    def sample_depths(self, lookups: int = 50) -> list[int]:
        """Seeded (origin, random-target) lookup depths — the sim's DHT
        scaling measurement."""
        out = []
        for _ in range(lookups):
            origin = self.peers[self.rng.randrange(len(self.peers))]
            target = _node_id(self.rng)
            out.append(self.lookup_depth(origin, target))
        return out
