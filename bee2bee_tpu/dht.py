"""DHT: key-value store + piece-provider announce/lookup.

Capability parity with reference dht (/root/reference/bee2bee/dht.py:6-64):
Kademlia-backed when the optional `kademlia` package is importable, in-memory
fallback otherwise. Provider records carry mesh-coordinate metadata so piece
lookup can prefer a provider that holds the exact shard for a requester's
mesh position (TPU-native extension; see pieces.ShardManifest).
"""

from __future__ import annotations

import json
import time
from typing import Any


class InMemoryDHT:
    """Single-process fallback store (reference dht.py:6-13)."""

    def __init__(self):
        self._store: dict[str, Any] = {}

    async def set(self, key: str, value: Any) -> None:
        self._store[key] = value

    async def get(self, key: str) -> Any:
        return self._store.get(key)

    def stop(self) -> None:
        self._store.clear()


class DHTNode:
    """DHT facade with graceful fallback (reference dht.py:17-64)."""

    def __init__(self, port: int = 8468):
        self.port = port
        self.server: Any = None
        self.fallback: InMemoryDHT | None = None
        self.started = False

    async def start(self, bootstrap: list[tuple[str, int]] | None = None) -> None:
        try:
            from kademlia.network import Server  # optional dep

            self.server = Server()
            await self.server.listen(self.port)
            if bootstrap:
                await self.server.bootstrap(bootstrap)
        except Exception:
            self.server = None
            self.fallback = InMemoryDHT()
        self.started = True

    async def stop(self) -> None:
        if self.server is not None:
            try:
                self.server.stop()
            except Exception:
                pass
            self.server = None
        if self.fallback is not None:
            self.fallback.stop()
            self.fallback = None
        self.started = False

    async def set(self, key: str, value: Any) -> None:
        if not self.started:
            await self.start()
        if self.server is not None:
            await self.server.set(key, json.dumps(value))
        else:
            await self.fallback.set(key, value)

    async def get(self, key: str) -> Any:
        if not self.started:
            await self.start()
        if self.server is not None:
            raw = await self.server.get(key)
            return json.loads(raw) if raw is not None else None
        return await self.fallback.get(key)

    # -- piece providers (reference dht.py:53-64, extended with shard coords) --

    async def announce_piece(
        self,
        piece_hash: str,
        node_addr: str,
        mesh_axis: str | None = None,
        shard_index: int | None = None,
    ) -> None:
        key = f"piece:{piece_hash}"
        providers = await self.get(key) or []
        rec = {
            "addr": node_addr,
            "mesh_axis": mesh_axis,
            "shard_index": shard_index,
            "ts": time.time(),
        }
        providers = [p for p in providers if p.get("addr") != node_addr]
        providers.append(rec)
        await self.set(key, providers)

    async def find_providers(
        self, piece_hash: str, shard_index: int | None = None
    ) -> list[dict]:
        providers = await self.get(f"piece:{piece_hash}") or []
        if shard_index is not None:
            exact = [p for p in providers if p.get("shard_index") == shard_index]
            if exact:
                return exact
        return providers

    async def announce_manifest(self, model: str, manifest_json: str, node_addr: str) -> None:
        """Publish a ShardManifest under its model name so joining peers can
        discover the piece set for a serving group."""
        await self.set(f"manifest:{model}", {"manifest": manifest_json, "addr": node_addr})

    async def get_manifest(self, model: str) -> dict | None:
        return await self.get(f"manifest:{model}")
