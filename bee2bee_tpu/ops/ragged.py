"""Ragged paged-attention pallas kernel: one decode path for paged +
flash + spec.

The dense paged path (models/core.forward's ``block_tables`` branch)
gathers every mapped block into a rectangular [B, S, Hkv, hd] view and
materializes [B, H, T, S] scores — the block pool saved cache HBM but
attention still paid the dense rectangle. This kernel (after "Ragged
Paged Attention" — PAPERS.md, arxiv 2604.15464) reads K/V straight from
the pool:

- **Pool-direct gather**: the pool is head-major ``[Hkv, NB, BS, hd]``
  (per-layer slice of core.init_paged_pool's ``[L, Hkv, NB, BS, hd]``)
  and the grid's page dimension DMAs exactly one block per step via a
  scalar-prefetched block-table lookup in the BlockSpec index_map —
  ``(h, tables[b, j], 0, 0)``. No gathered view, no [T, S] score
  materialization; per-step cache traffic is the table width, same as
  the pool's design point. Every tensor operand's trailing block dims
  are ``(rows, hd)`` — Mosaic-tileable (the [NB, BS, Hkv, hd] layout
  would put a 1-blocked head axis second-to-last and fail to lower, and
  a bool-mask operand blocked per 16-lane page would violate the same
  rule — the constraint that shaped ops/flash.py's head-major layout).
- **One kernel, every chunk shape**: queries fold to ``[B, Hkv, G*T,
  hd]`` rows (GQA group g major, chunk position t minor), so [B, 1]
  decode, [B, K+1] spec verify and ragged prefill chunks are all just
  different row counts of the same program. Rows tile over a q grid
  dimension so long prefill chunks bound VMEM.
- **Scalar-compact semantics**: no mask array at all. Causality and
  per-row ragged lengths derive from the prefetched per-row ``offset``;
  the sliding window (and the gemma-2/3 per-layer local/global
  alternation) arrives as ONE prefetched int32 ``window`` (0 = full
  causal) that core.forward selects per layer with the SAME
  is_sliding_layer rule the dense mask builder uses; logit softcap and
  the gemma score-scale override are scalar params. Null-block table
  entries past a row's live extent are beyond ``offset + T`` and
  therefore causally masked by construction. Two block-level skip
  predicates (page past the causal frontier / entirely below the
  window) avoid the dead MXU/VPU work on those pages — the BlockSpec
  gather still DMAs every table-width page into VMEM (skipping the DMA
  itself needs an index_map that can remap dead pages, a follow-up) —
  so the compute cost of windowed decode follows ~ceil(w/BS) pages
  while cache traffic remains the (pow2-bucketed) table width. ALiBi
  stays dense-only (the bias needs absolute key positions per head;
  the engine validates).
- **Online softmax** over the page iterations with f32 m/l/acc VMEM
  scratch, f32 MXU accumulation, storage dtype out — exactly
  ops/flash.py's numerics, so greedy parity with the dense path holds
  token-for-token.

- **Int8 pool dequant in the page loop**: with ``k_scale``/``v_scale``
  [Hkv, NB] f32 (the per-layer slice of core.init_paged_pool's
  per-page-per-head quantization scales), the pool blocks arrive int8
  and each grid step dequantizes ITS one block in VMEM — K before the
  QK^T dot, V before the PV dot — so the precision change rides the
  existing gather: HBM cache traffic halves and nothing wider than one
  block ever materializes. The scales ride the SAME scalar-prefetch
  channel as the block tables — pre-gathered through the tables to
  ``[Hkv, B, MB]`` outside the kernel, so the kernel reads one f32 per
  grid step at ``[h, b, j]`` from SMEM (a (1, 1)-blocked VMEM operand
  would violate the trailing-dims tiling rule above) and the SMEM
  footprint is table-sized — 2 * Hkv/shard * B * MB * 4 bytes, bounded
  by the pow2-bucketed LIVE width like every per-step operand, never by
  pool capacity. The f32 m/l/acc scratch already isolates accumulation
  from storage precision, so the quantized path changes no softmax math.

Off-TPU the kernel runs in pallas interpret mode (the `_on_tpu()` /
`interpret` pattern from ops/flash.py), so the CPU test suite exercises
the exact kernel code path.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..compat import shard_map
from .flash import NEG_INF, _LANES, _on_tpu, validate_flash_mesh


def _ragged_kernel(
    tables_ref,  # SMEM [B, MB] int32 (scalar-prefetch): per-row block tables
    off_ref,  # SMEM [B] int32 (scalar-prefetch): position of q[:, 0]
    win_ref,  # SMEM [1] int32 (scalar-prefetch): sliding window (0 = none)
    *refs,
    # quantized=True prepends two more scalar-prefetch refs:
    #   kscale_ref, vscale_ref  SMEM [Hkv, B, MB] f32 scales, pre-gathered
    #                           through the block tables per row
    # then the tensor operands either way:
    #   q_ref    [1, 1, BQ, hd]  q rows: GQA group g major, chunk pos t minor
    #   k_ref    [1, 1, BS, hd]  one pool block, gathered via index_map
    #   v_ref    [1, 1, BS, hd]
    #   o_ref    [1, 1, BQ, hd]
    #   m_ref    VMEM [BQ, 128] f32 running max
    #   l_ref    VMEM [BQ, 128] f32 running sum
    #   acc_ref  VMEM [BQ, hd] f32
    sm_scale: float,
    softcap: float,
    block_size: int,
    block_q: int,
    chunk: int,  # T: query positions per row (row r is chunk position r % T)
    quantized: bool = False,
):
    if quantized:
        (kscale_ref, vscale_ref, q_ref, k_ref, v_ref,
         o_ref, m_ref, l_ref, acc_ref) = refs
    else:
        kscale_ref = vscale_ref = None
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    h = pl.program_id(1)
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    off = off_ref[b]
    win = win_ref[0]
    # block-level skips, mirroring ops/flash.py's above-diagonal skip:
    # a page starting past the causal frontier (every query position is
    # <= off + chunk - 1) or ending below every query's window start
    # (>= off - win + 1 when the window binds) contributes nothing
    past_causal = j * block_size > off + chunk - 1
    below_window = (win > 0) & (j * block_size + block_size - 1 < off - win + 1)

    @pl.when(jnp.logical_not(past_causal | below_window))
    def _attend():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        if quantized:
            # every key/value row of this block shares ONE scale per kv
            # head: the wrapper pre-gathered the per-page scales through
            # the block tables to [Hkv, B, MB], so the grid coordinates
            # index them directly and the dequant touches only the one
            # block already resident in VMEM
            k = (k.astype(jnp.float32) * kscale_ref[h, b, j]).astype(q.dtype)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * sm_scale
        )  # [BQ, BS]
        if softcap:  # gemma-2: tanh cap BEFORE masking, like core._attention
            s = jnp.tanh(s / softcap) * softcap
        # visibility from scalars: query row r sits at chunk position
        # (i*BQ + r) % T, key column c at pool position j*BS + c
        row = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_size), 0)
        qpos = off + (i * block_q + row) % chunk
        kvpos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_size), 1
        )
        msk = kvpos <= qpos
        msk = msk & ((win <= 0) | (kvpos > qpos - win))
        s = jnp.where(msk, s, NEG_INF)

        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        # a fully-masked ROW would otherwise contribute exp(-1e30+1e30)=1
        p = jnp.where(msk, jnp.exp(s - m_new[:, None]), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)

        v = v_ref[0, 0]
        if quantized:
            v = (v.astype(jnp.float32) * vscale_ref[h, b, j]).astype(q.dtype)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha[:, None] + pv
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(j == pl.num_programs(3) - 1)
    def _finalize():
        # l == 0 only for rows with nothing visible (every page skipped —
        # can't happen for live rows, but a dead batch row's stale offset
        # may land there): emit 0, not 0/0 = NaN
        l = l_ref[:, 0][:, None]
        o_ref[0, 0] = (
            acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
        ).astype(o_ref.dtype)


def ragged_paged_attention(
    q,  # [B, T, H, hd]
    k_pool,  # [Hkv, NB, BS, hd] — per-layer slice of the paged pool
    v_pool,  # [Hkv, NB, BS, hd]
    block_tables,  # [B, MB] int32: pool block ids per row (0 = null block)
    offset,  # [] or [B] int32: global position of q[:, 0]
    window=None,  # [] or [1] int32 (traced ok) or python int: sliding
    #               window for THIS call's layer; None/0 = full causal
    sm_scale: float | None = None,
    logit_softcap: float = 0.0,
    block_q: int = 256,
    interpret: bool | None = None,
    k_scale=None,  # [Hkv, NB] f32: int8-pool per-page-per-head scales;
    v_scale=None,  # both present = quantized pool, dequant in-kernel
):
    """Causal attention for a [B, T] chunk over the paged pool; returns
    [B, T, H*hd] (core._attention ABI). T=1 is decode, T=K+1 spec verify,
    T=bucket a ragged prefill chunk — one compiled program per (T, table
    width) pair, both already bucketed by the engine. With
    ``k_scale``/``v_scale`` the pool is int8 (core.init_paged_pool's
    quantized layout) and each gathered block dequantizes in VMEM before
    its dot — same grid, same softmax math, half the pool HBM traffic."""
    B, T, H, hd = q.shape
    Hkv, NB, BS, _ = k_pool.shape
    MB = block_tables.shape[1]
    G = H // Hkv
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(hd)
    interpret = (not _on_tpu()) if interpret is None else interpret
    quantized = k_scale is not None
    if quantized and v_scale is None:
        raise ValueError("quantized pool needs BOTH k_scale and v_scale")

    nq = G * T
    bq = min(block_q, max(nq, 8))
    nqp = -(-nq // bq) * bq
    # [B, T, H, hd] -> [B, Hkv, G*T, hd]: head h = kvh*G + g attends kv
    # head kvh = h // G, so heads of one group are contiguous rows
    qT = q.reshape(B, T, Hkv, G, hd).transpose(0, 2, 3, 1, 4).reshape(B, Hkv, nq, hd)
    if nqp != nq:
        qT = jnp.pad(qT, ((0, 0), (0, 0), (0, nqp - nq), (0, 0)))

    tables = jnp.asarray(block_tables, jnp.int32)
    off = jnp.broadcast_to(
        jnp.asarray(offset if offset is not None else 0, jnp.int32).reshape(-1),
        (B,),
    )
    win = jnp.asarray(window if window is not None else 0, jnp.int32).reshape(-1)[:1]

    grid = (B, Hkv, nqp // bq, MB)
    kernel = functools.partial(
        _ragged_kernel,
        sm_scale=sm_scale,
        softcap=float(logit_softcap or 0.0),
        block_size=BS,
        block_q=bq,
        chunk=T,
        quantized=quantized,
    )
    # index maps take the scalar-prefetch refs as trailing args (3 of
    # them, or 5 with the quantization scales — the variadic tail keeps
    # one lambda serving both); the K/V maps ARE the gather — page j of
    # row b reads pool block tables[b, j]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5 if quantized else 3,
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (1, 1, bq, hd), lambda b, h, i, j, tb, *_: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, BS, hd), lambda b, h, i, j, tb, *_: (h, tb[b, j], 0, 0)
            ),
            pl.BlockSpec(
                (1, 1, BS, hd), lambda b, h, i, j, tb, *_: (h, tb[b, j], 0, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bq, hd), lambda b, h, i, j, tb, *_: (b, h, i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, hd), jnp.float32),
        ],
    )
    # pre-gather the per-page scales through the block tables OUTSIDE the
    # kernel: the SMEM operand is then [Hkv, B, MB] — bounded by the
    # pow2-bucketed LIVE table width like every other per-step operand —
    # instead of the pool-sized [Hkv, NB], which scales with total
    # capacity and would overflow SMEM on production-sized pools. The
    # gather itself is B*MB*Hkv f32 per call — noise next to one block's
    # page traffic — and the kernel then indexes (h, b, j) directly.
    scales = (
        (
            jnp.asarray(k_scale, jnp.float32)[:, tables],
            jnp.asarray(v_scale, jnp.float32)[:, tables],
        )
        if quantized
        else ()
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, nqp, hd), q.dtype),
        interpret=interpret,
    )(tables, off, win, *scales, qT, k_pool, v_pool)
    # [B, Hkv, nqp, hd] -> [B, T, H*hd]
    out = out[:, :, :nq].reshape(B, Hkv, G, T, hd).transpose(0, 3, 1, 2, 4)
    return out.reshape(B, T, H * hd)


# ----------------------------------------------------- TP/mesh wrapper


def make_ragged_attn_fn(mesh=None, interpret: bool | None = None):
    """Build an attn_fn (core.transformer_block ABI) that reads the paged
    pool directly. core.forward marks it via the ``ragged`` attribute: on
    the block-tables path the kv_hook hands the POOL SLICES through as
    (k, v), forward partials in the block tables, and the per-layer mask
    argument becomes the compact [1] int32 window selector
    (core.make_layer_window) instead of a bool mask — nothing S-wide is
    ever built. On an int8 pool the hook hands (pool slice, [Hkv, NB]
    scale slice) TUPLES through and the kernel dequantizes per gathered
    block.

    Under a non-trivial mesh the kernel runs per-shard via shard_map
    (pallas_call has no SPMD partitioning rule): q heads and the pool's
    kv-head dim shard over `model` (replicated for MQA — the flash
    kernel's head-layout rules, enforced by validate_flash_mesh),
    batch/tables/offsets over `data` when it divides; the window scalar
    replicates. The pool's block/slot dims never shard here — any row
    gathers arbitrary blocks (partition.paged_cache_spec).

    Called WITHOUT block tables (a no-cache forward that still passes an
    attn_fn), it falls back to the dense reference — correctness over
    speed on a path that never serves decode (`mask` is a REAL bool mask
    there; core.forward only swaps in the window selector on the
    block-tables path).
    """
    from jax.sharding import PartitionSpec as P

    def attn(q, k, v, mask, cfg, positions=None, block_tables=None):
        if block_tables is None:
            from ..models.core import _attention

            return _attention(q, k, v, mask, cfg)
        # int8 pool: the kv_hook hands (pool slice, scale slice) pairs
        # through — unpack them here so the kernel dequants in-loop
        k_scale = v_scale = None
        if isinstance(k, tuple):
            k, k_scale = k
            v, v_scale = v
        window = mask  # the ragged path's per-layer [1] int32 selector
        offset = positions[:, 0] if positions is not None else None
        sm_scale = 1.0 / math.sqrt(cfg.attn_scale or cfg.head_dim)
        softcap = float(cfg.attn_logit_softcap or 0.0)
        if mesh is None or all(n == 1 for n in mesh.shape.values()):
            return ragged_paged_attention(
                q, k, v, block_tables, offset, window,
                sm_scale=sm_scale, logit_softcap=softcap, interpret=interpret,
                k_scale=k_scale, v_scale=v_scale,
            )
        B = q.shape[0]
        Hkv = k.shape[0]
        tp = mesh.shape.get("model", 1)
        data = mesh.shape.get("data", 1)
        batch_ax = "data" if data > 1 and B % data == 0 else None
        head_ax = "model" if tp > 1 else None
        kv_ax = "model" if tp > 1 and Hkv % tp == 0 else None
        off = jnp.broadcast_to(
            jnp.asarray(offset if offset is not None else 0, jnp.int32).reshape(-1),
            (B,),
        )
        win = jnp.asarray(
            window if window is not None else 0, jnp.int32
        ).reshape(-1)[:1]
        # ONE shard_map for both pool precisions: the int8 scales shard
        # exactly like the pool's kv-head dim (their block dim, like the
        # pool's, never shards) and simply extend the operand tuple
        quant = k_scale is not None
        scale_args = (k_scale, v_scale) if quant else ()

        def body(q_, k_, v_, t_, o_, w_, *sc):
            return ragged_paged_attention(
                q_, k_, v_, t_, o_, w_,
                sm_scale=sm_scale, logit_softcap=softcap, interpret=interpret,
                k_scale=sc[0] if sc else None,
                v_scale=sc[1] if sc else None,
            )

        mapped = shard_map(
            body,
            mesh=mesh,
            in_specs=(
                P(batch_ax, None, head_ax, None),
                P(kv_ax),
                P(kv_ax),
                P(batch_ax),
                P(batch_ax),
                P(),
            ) + (P(kv_ax),) * len(scale_args),
            out_specs=P(batch_ax, None, head_ax),
            check_vma=False,
        )
        return mapped(
            q, k, v, jnp.asarray(block_tables, jnp.int32), off, win,
            *scale_args,
        )

    attn.ragged = True
    return attn


def validate_ragged_mesh(cfg, mesh) -> None:
    """Head-layout rules for the pool-direct kernel — identical to the
    rectangular flash kernel's (q heads divide `model`; GQA KV must shard,
    only MQA may replicate), so the one validator serves both."""
    validate_flash_mesh(cfg, mesh)
