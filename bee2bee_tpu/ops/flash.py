"""Flash attention pallas kernels (prefill + KV-cache decode).

Design notes (pallas_guide.md patterns):
- Online softmax: grid's innermost dim walks K/V blocks sequentially on
  one core; m/l/acc scratch in VMEM persists across those iterations and
  the output block is written on the last one.
- Accumulation in f32 (MXU `preferred_element_type`), storage dtype of
  the inputs.
- GQA: the kv-head index for a q-head h is h // (H // Hkv), computed in
  the BlockSpec index_map so each q-head grid step DMAs only its own KV
  block.
- `offset` rides SMEM as a [1,1] scalar so the SAME compiled kernel
  serves prefill (offset=0 mask within the chunk) and cached decode
  (queries live at positions offset..offset+T).
- Off-TPU the kernels run in pallas interpret mode — the CPU test suite
  exercises the exact kernel code path.

Replaces the dense [B,H,T,S] score materialization of models/core
._attention on the hot path (engine flag attention="flash").
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128  # m/l scratch lane padding (min f32 tile is (8, 128))


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


# ------------------------------------------------------------- prefill


def _flash_kernel(
    off_ref,  # SMEM [B] int32 (scalar-prefetch): global position of q[:, 0]
    q_ref,  # [1, 1, BQ, hd]  (head-major layout: Mosaic requires the
    k_ref,  # [1, 1, BK, hd]   trailing two block dims to be (8,128)-tileable
    v_ref,  # [1, 1, BK, hd]   or dim-equal — [.., seq_block, hd] is; the
    o_ref,  # [1, 1, BQ, hd]   head axis blocked at 1 in trailing position
    m_ref,  # VMEM [BQ, 128] f32 running max         is NOT and fails to lower)
    l_ref,  # VMEM [BQ, 128] f32 running sum
    acc_ref,  # VMEM [BQ, hd] f32
    *,
    sm_scale: float,
    block_q: int,
    block_k: int,
    causal: bool,
):
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    off = off_ref[pl.program_id(0)]

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # skip K blocks entirely above the diagonal (offset is dynamic, so the
    # grid can't be pruned statically — predicate out the wasted MXU work)
    last_qpos = off + (qi + 1) * block_q - 1
    visible = (kj * block_k <= last_qpos) if causal else jnp.bool_(True)

    @pl.when(visible)
    def _attend():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * sm_scale
        )  # [BQ, BK]

        if causal:
            qpos = off + qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            mask = kpos <= qpos
            s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        if causal:
            # a fully-masked ROW would otherwise contribute exp(-1e30+1e30)=1
            p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)

        v = v_ref[0, 0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha[:, None] + pv
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(kj == pl.num_programs(3) - 1)
    def _finalize():
        # l == 0 only for rows with no visible keys (e.g. a decode row whose
        # lengths[b] == 0, offset -1): emit 0, not 0/0 = NaN
        l = l_ref[:, 0][:, None]
        o_ref[0, 0] = (
            acc_ref[:] / jnp.where(l == 0.0, 1.0, l)
        ).astype(o_ref.dtype)


def flash_attention(
    q,  # [B, T, H, hd]
    k,  # [B, S, Hkv, hd]
    v,  # [B, S, Hkv, hd]
    offset=None,  # [] or [B] int32: global position of q[:, 0] (None -> 0)
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    sm_scale: float | None = None,
    interpret: bool | None = None,
):
    """Tiled causal attention; returns [B, T, H*hd] (core._attention ABI).

    T and S are padded to the block sizes internally; with a KV cache pass
    S = cache capacity and `offset` = write position (future cache slots
    are masked by causality exactly like models/core.forward's mask).
    """
    B, T, H, hd = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    group = H // Hkv
    sm_scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(hd)
    interpret = (not _on_tpu()) if interpret is None else interpret

    block_q = min(block_q, max(T, 8))
    block_k = min(block_k, max(S, 8))
    Tp = -(-T // block_q) * block_q
    Sp = -(-S // block_k) * block_k
    # head-major layout [B, H(kv), seq, hd]: the kernel's trailing block
    # dims become (seq_block, hd), which Mosaic can tile; the original
    # [B, seq, H, hd] layout put the head axis (blocked at 1) second-to-
    # last and failed to lower on real TPU
    qT = jnp.transpose(q, (0, 2, 1, 3))
    kT = jnp.transpose(k, (0, 2, 1, 3))
    vT = jnp.transpose(v, (0, 2, 1, 3))
    if Tp != T:
        qT = jnp.pad(qT, ((0, 0), (0, 0), (0, Tp - T), (0, 0)))
    if Sp != S:
        kT = jnp.pad(kT, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
        vT = jnp.pad(vT, ((0, 0), (0, 0), (0, Sp - S), (0, 0)))
    if not causal and Sp != S:
        raise ValueError("non-causal flash requires S divisible by block_k")

    # per-batch offsets ride whole into SMEM via scalar prefetch — a
    # blocked [B,1] SMEM operand hits the same Mosaic trailing-dims rule
    off = jnp.broadcast_to(
        jnp.asarray(offset if offset is not None else 0, jnp.int32).reshape(-1),
        (B,),
    )

    grid = (B, H, Tp // block_q, Sp // block_k)
    kernel = functools.partial(
        _flash_kernel,
        sm_scale=sm_scale,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
    )
    # index maps take the scalar-prefetch ref as a trailing arg
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda b, h, i, j, off: (b, h, i, 0)),
            pl.BlockSpec(
                (1, 1, block_k, hd), lambda b, h, i, j, off: (b, h // group, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, hd), lambda b, h, i, j, off: (b, h // group, j, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, hd), lambda b, h, i, j, off: (b, h, i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Tp, hd), q.dtype),
        interpret=interpret,
    )(off, qT, kT, vT)
    # [B, H, Tp, hd] -> [B, T, H*hd]
    return jnp.transpose(out[:, :, :T], (0, 2, 1, 3)).reshape(B, T, H * hd)


# ----------------------------------------------------- mesh validation
# (make_flash_attn_fn — the rectangular-cache engine wrapper — is gone
# with the rectangular cache itself: the engine's attention="flash" now
# runs the ragged paged kernel, ops/ragged.make_ragged_attn_fn, which
# reuses this kernel's head-layout rules below. flash_attention stays as
# the contiguous-K/V kernel: scoring/offline shapes and the kernel-level
# numerics tests.)


def validate_flash_mesh(cfg, mesh) -> None:
    """Fail fast when the head layout cannot run head-local flash:
    q heads must divide the `model` axis, and each shard's q-head count
    must cover its kv heads whole (GQA group stays integral)."""
    tp = mesh.shape.get("model", 1)
    if tp <= 1:
        return
    H, Hkv = cfg.n_heads, cfg.n_kv_heads
    if H % tp:
        raise ValueError(
            f"attention='flash' needs n_heads={H} divisible by model axis "
            f"{tp} (head-local kernel); use attention='dense'"
        )
    if Hkv % tp == 0:
        return  # sharded KV: local h // G maps to the correct local kv head
    if Hkv != 1:
        # replicated KV with Hkv > 1: shard s's local q heads all belong to
        # kv heads near s*H/tp/G globally, but the kernel's LOCAL
        # h // (H_local/Hkv) mapping would spread them over all Hkv heads —
        # silently wrong attention. Only MQA (Hkv == 1, every q head -> kv 0)
        # is layout-invariant under replication.
        raise ValueError(
            f"attention='flash' cannot run GQA with n_kv_heads={Hkv} "
            f"replicated across model axis {tp} (local kv-head mapping "
            "would be wrong); use attention='dense'"
        )


# Decode (T=1) rides the SAME kernel shape: flash_attention with a
# [B, 1, H, hd] query and offset = write position pads to one 8-row q
# block per head. The ENGINE's decode no longer comes through here — the
# paged pool is the only cache layout and attention="flash" runs the
# ragged paged kernel (ops/ragged.py) — but the T=1 contract stays
# tested in tests/test_ops_flash.py as the contiguous-K/V reference.
