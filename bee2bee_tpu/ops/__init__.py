"""Hand-written TPU kernels (pallas/Mosaic).

The reference has zero native/kernel code (SURVEY §2 native inventory:
"none"); on TPU the kernel obligations come from the target itself —
flash attention tiles that keep the MXU fed from VMEM instead of
materializing [T, S] score matrices in HBM.

Kernels auto-fall back to interpret mode off-TPU, so the whole test
suite exercises them on the CPU mesh.
"""

from .flash import flash_attention  # noqa: F401
from .ragged import ragged_paged_attention  # noqa: F401
